"""In-memory cluster world model + builder helpers.

A :class:`World` is the hermetic backing store for :class:`MockClusterClient`
and the output of the synthetic-cascade generators.  It plays the role of the
reference's hand-written mock state (reference: utils/mock_k8s_client.py
builds ~1,300 lines of literal dicts in ``__init__``) but is constructed
programmatically from small builder functions, so worlds of 5 or 50,000
services come from the same code path.

All objects are Kubernetes-API-shaped plain dicts.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, List, Optional

MOCK_TIME = "2026-01-01T00:00:00Z"


def _ns_map() -> Dict[str, list]:
    return {}


@dataclasses.dataclass
class World:
    """Full cluster state, keyed by namespace where applicable."""

    cluster_name: str = "rca-mock-cluster"
    # namespace -> list of objects
    pods: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    services: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    deployments: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    statefulsets: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    daemonsets: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    cronjobs: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    events: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    endpoints: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    ingresses: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    network_policies: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    configmaps: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    secrets: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    pvcs: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    resource_quotas: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    hpas: Dict[str, List[dict]] = dataclasses.field(default_factory=_ns_map)
    # namespace -> pod -> container -> log text
    logs: Dict[str, Dict[str, Dict[str, str]]] = dataclasses.field(default_factory=dict)
    previous_logs: Dict[str, Dict[str, Dict[str, str]]] = dataclasses.field(
        default_factory=dict
    )
    # namespace -> {"pods": {pod: {"containers": {c: {...}}, "cpu": .., "memory": ..}}}
    pod_metrics: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    # cluster-scoped
    nodes: List[dict] = dataclasses.field(default_factory=list)
    node_metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # traces: {"trace_ids": {...}, "traces": {...}, "latency": {...},
    #          "error_rates": {...}, "dependencies": {...}, "slow_ops": [...]}
    traces: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # ground truth for synthetic worlds (fault-injection bookkeeping)
    ground_truth: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # -- mutation journal (the watch surface; VERDICT r2 item 6) ----------
    # Incremental-change feed backing MockClusterClient.watch_changes, the
    # hermetic twin of kubernetes watch streams.  Mutations made through
    # the real K8s API always pass the API server; in the mock, DIRECT
    # dict edits are "out-of-band" — call :meth:`touch` after one (or use
    # :meth:`add`, which journals automatically) for a watcher to see it.
    journal: List[dict] = dataclasses.field(default_factory=list)
    journal_seq: int = 0
    journal_cap: int = 10_000  # older entries trim; stale cursors expire
    journal_floor: int = 0     # seq of the oldest retained entry

    # -- derived lookup/columnar state (never part of the world's value) --
    # per-(store, namespace) name->position index: touch() was a linear
    # scan per mutation, which made building a 100k-pod world quadratic
    # (~2 min at 10k pods just stamping resourceVersions).  Verified on
    # access (list identity + length + name-at-position), rebuilt on any
    # mismatch, so out-of-band list surgery degrades to a rebuild, never
    # to a wrong stamp.
    _pos_index: Dict[tuple, dict] = dataclasses.field(
        default_factory=dict, init=False, repr=False, compare=False,
    )
    # namespace -> ColumnarWorld master (rca_tpu.cluster.columnar),
    # created lazily by MockClusterClient.get_columnar
    _columnar: Dict[str, Any] = dataclasses.field(
        default_factory=dict, init=False, repr=False, compare=False,
    )

    def namespaces(self) -> List[str]:
        names = set()
        for store in (self.pods, self.services, self.deployments, self.events):
            names.update(store.keys())
        return sorted(names) or ["default"]

    def touch(self, kind: str, namespace: str, name: str) -> None:
        """Record that object ``kind``/``name`` changed (create, update, or
        delete — watchers re-fetch, so the op is irrelevant).  ``kind`` is
        the singular store name ("pod", "service", ...) plus the pseudo
        kinds "pod_metrics", "event", and "logs".

        Mirrors the API server's write semantics by bumping the touched
        object's ``metadata.resourceVersion`` (to the journal seq): real
        clusters stamp every write, and the incremental feature extractor
        (features/extract.py) keys its row cache on it — a mock whose
        mutations kept a frozen rv would make that cache untestable."""
        self.journal_seq += 1
        store_name = self._KIND_PLURAL.get(kind, "")
        store = getattr(self, store_name, None)
        if isinstance(store, dict):
            obj = self.find(store_name, namespace, name)
            if obj is not None:
                md = obj.get("metadata")
                if isinstance(md, dict):
                    md["resourceVersion"] = str(self.journal_seq)
        self.journal.append(
            {"seq": self.journal_seq, "kind": kind,
             "namespace": namespace, "name": name}
        )
        if len(self.journal) > self.journal_cap:
            drop = len(self.journal) - self.journal_cap
            del self.journal[:drop]
            self.journal_floor = self.journal[0]["seq"]

    def changes_since(self, seq: int) -> Optional[List[dict]]:
        """Journal entries after ``seq``; None = expired (trimmed past).

        A cursor at ``floor - 1`` is still complete — it needs entries
        from ``floor`` onward, all of which are retained; only cursors
        strictly older than that have lost entries to the trim."""
        if seq < self.journal_floor - 1:
            return None
        return [e for e in self.journal if e["seq"] > seq]

    _KIND_PLURAL = {
        "pod": "pods", "service": "services", "deployment": "deployments",
        "statefulset": "statefulsets", "daemonset": "daemonsets",
        "cronjob": "cronjobs", "event": "events", "endpoints": "endpoints",
        "ingress": "ingresses", "networkpolicy": "network_policies",
        "configmap": "configmaps", "secret": "secrets", "pvc": "pvcs",
        "resourcequota": "resource_quotas", "hpa": "hpas",
    }

    _KIND_SINGULAR = {
        "pods": "pod", "services": "service", "deployments": "deployment",
        "statefulsets": "statefulset", "daemonsets": "daemonset",
        "cronjobs": "cronjob", "events": "event", "endpoints": "endpoints",
        "ingresses": "ingress", "network_policies": "networkpolicy",
        "configmaps": "configmap", "secrets": "secret", "pvcs": "pvc",
        "resource_quotas": "resourcequota", "hpas": "hpa",
    }

    # -- O(1) name lookup (verified position index) -----------------------
    def _index_for(self, store_name: str, namespace: str, lst: list) -> dict:
        key = (store_name, namespace)
        idx = self._pos_index.get(key)
        if idx is None or idx["id"] != id(lst) or idx["len"] != len(lst):
            pos: Dict[str, int] = {}
            dup = False
            for i, obj in enumerate(lst):
                n = (obj.get("metadata") or {}).get("name", "")
                if n in pos:
                    dup = True
                pos[n] = i
            idx = {"id": id(lst), "len": len(lst), "pos": pos, "dup": dup}
            self._pos_index[key] = idx
        return idx

    def find(self, store_name: str, namespace: str, name: str
             ) -> Optional[dict]:
        """The object named ``name`` in store ``store_name`` (the PLURAL
        spelling, e.g. "pods"), or None.  O(1) via the position index;
        a stale position (out-of-band list surgery) rebuilds and retries,
        so the answer always reflects the live list."""
        store = getattr(self, store_name, None)
        if not isinstance(store, dict):
            return None
        lst = store.get(namespace, [])
        idx = self._index_for(store_name, namespace, lst)
        pos = idx["pos"].get(name)
        if pos is None:
            return None
        obj = lst[pos] if pos < len(lst) else None
        if obj is None or (obj.get("metadata") or {}).get("name") != name:
            # positions shifted under a same-length rewrite: rebuild once
            del self._pos_index[(store_name, namespace)]
            idx = self._index_for(store_name, namespace, lst)
            pos = idx["pos"].get(name)
            obj = lst[pos] if pos is not None else None
        return obj

    def store_degenerate(self, store_name: str, namespace: str) -> bool:
        """True when the store holds duplicate object names — name-keyed
        incremental maintenance (columnar tables) must fall back to the
        dict scans there."""
        store = getattr(self, store_name, None)
        if not isinstance(store, dict):
            return False
        lst = store.get(namespace, [])
        return self._index_for(store_name, namespace, lst)["dup"]

    def add(self, kind: str, namespace: str, obj: dict) -> dict:
        lst = getattr(self, kind).setdefault(namespace, [])
        lst.append(obj)
        # keep the position index warm across appends (a rebuild per add
        # would make bulk world construction quadratic again)
        idx = self._pos_index.get((kind, namespace))
        if idx is not None and idx["id"] == id(lst) \
                and idx["len"] == len(lst) - 1:
            n = (obj.get("metadata") or {}).get("name", "")
            if n in idx["pos"]:
                idx["dup"] = True
            idx["pos"][n] = len(lst) - 1
            idx["len"] = len(lst)
        self.touch(
            self._KIND_SINGULAR.get(kind, kind), namespace,
            obj.get("metadata", {}).get("name", "")
            or obj.get("involvedObject", {}).get("name", ""),
        )
        return obj


# ---------------------------------------------------------------------------
# Builder helpers
# ---------------------------------------------------------------------------


def meta(name: str, namespace: Optional[str] = None, labels: Optional[dict] = None,
         **extra: Any) -> Dict[str, Any]:
    m: Dict[str, Any] = {"name": name, "creationTimestamp": MOCK_TIME}
    if namespace is not None:
        m["namespace"] = namespace
    if labels:
        m["labels"] = dict(labels)
    m.update(extra)
    return m


def container_spec(
    name: str,
    image: str = "busybox:1.36",
    requests: Optional[dict] = None,
    limits: Optional[dict] = None,
    env: Optional[List[dict]] = None,
    env_from: Optional[List[dict]] = None,
    volume_mounts: Optional[List[dict]] = None,
) -> Dict[str, Any]:
    spec: Dict[str, Any] = {"name": name, "image": image}
    resources: Dict[str, Any] = {}
    if requests:
        resources["requests"] = requests
    if limits:
        resources["limits"] = limits
    if resources:
        spec["resources"] = resources
    if env:
        spec["env"] = env
    if env_from:
        spec["envFrom"] = env_from
    if volume_mounts:
        spec["volumeMounts"] = volume_mounts
    return spec


def running_status(name: str, restarts: int = 0, ready: bool = True) -> Dict[str, Any]:
    return {
        "name": name,
        "ready": ready,
        "restartCount": restarts,
        "state": {"running": {"startedAt": MOCK_TIME}},
    }


def waiting_status(
    name: str,
    reason: str,
    message: str = "",
    restarts: int = 0,
    last_exit_code: Optional[int] = None,
    last_reason: str = "Error",
) -> Dict[str, Any]:
    status: Dict[str, Any] = {
        "name": name,
        "ready": False,
        "restartCount": restarts,
        "state": {"waiting": {"reason": reason, "message": message}},
    }
    if last_exit_code is not None:
        status["lastState"] = {
            "terminated": {
                "exitCode": last_exit_code,
                "reason": last_reason,
                "message": message,
            }
        }
    return status


def terminated_status(
    name: str,
    exit_code: int,
    reason: str = "Error",
    message: str = "",
    restarts: int = 0,
) -> Dict[str, Any]:
    term = {"exitCode": exit_code, "reason": reason, "message": message}
    return {
        "name": name,
        "ready": False,
        "restartCount": restarts,
        "state": {"terminated": dict(term)},
        "lastState": {"terminated": dict(term)},
    }


def make_pod(
    name: str,
    namespace: str,
    app: str,
    phase: str = "Running",
    containers: Optional[List[dict]] = None,
    container_statuses: Optional[List[dict]] = None,
    init_container_statuses: Optional[List[dict]] = None,
    conditions: Optional[List[dict]] = None,
    node_name: str = "node-0",
    volumes: Optional[List[dict]] = None,
    labels: Optional[dict] = None,
) -> Dict[str, Any]:
    if containers is None:
        containers = [container_spec(app,
                                     requests={"cpu": "100m", "memory": "64Mi"},
                                     limits={"cpu": "200m", "memory": "128Mi"})]
    if container_statuses is None:
        container_statuses = [running_status(c["name"]) for c in containers]
    ready = all(cs.get("ready") for cs in container_statuses) and phase == "Running"
    if conditions is None:
        conditions = [{"type": "Ready", "status": "True" if ready else "False"}]
    pod_labels = {"app": app}
    if labels:
        pod_labels.update(labels)
    spec: Dict[str, Any] = {"containers": containers, "nodeName": node_name}
    if volumes:
        spec["volumes"] = volumes
    status: Dict[str, Any] = {
        "phase": phase,
        "conditions": conditions,
        "containerStatuses": container_statuses,
        "startTime": MOCK_TIME,
    }
    if init_container_statuses:
        status["initContainerStatuses"] = init_container_statuses
    return {
        "metadata": meta(name, namespace, pod_labels),
        "spec": spec,
        "status": status,
    }


def make_deployment(
    name: str,
    namespace: str,
    app: str,
    replicas: int = 1,
    ready_replicas: Optional[int] = None,
    available_replicas: Optional[int] = None,
    selector: Optional[dict] = None,
    template_labels: Optional[dict] = None,
    containers: Optional[List[dict]] = None,
) -> Dict[str, Any]:
    if ready_replicas is None:
        ready_replicas = replicas
    if available_replicas is None:
        available_replicas = ready_replicas
    selector = selector or {"matchLabels": {"app": app}}
    template_labels = template_labels or {"app": app}
    return {
        "metadata": meta(name, namespace, {"app": app}),
        "spec": {
            "replicas": replicas,
            "selector": selector,
            "template": {
                "metadata": {"labels": template_labels},
                "spec": {
                    "containers": containers
                    or [container_spec(app,
                                       requests={"cpu": "100m", "memory": "64Mi"},
                                       limits={"cpu": "200m", "memory": "128Mi"})]
                },
            },
        },
        "status": {
            "replicas": replicas,
            "readyReplicas": ready_replicas,
            "availableReplicas": available_replicas,
            "updatedReplicas": replicas,
        },
    }


def make_service(
    name: str,
    namespace: str,
    selector: Optional[dict] = None,
    port: int = 80,
    target_port: int = 8080,
    service_type: str = "ClusterIP",
) -> Dict[str, Any]:
    return {
        "metadata": meta(name, namespace, {"app": name}),
        "spec": {
            "selector": selector if selector is not None else {"app": name},
            "ports": [{"port": port, "targetPort": target_port, "protocol": "TCP"}],
            "type": service_type,
        },
        "status": {},
    }


def make_endpoints(
    name: str, namespace: str, pod_names: List[str], port: int = 8080
) -> Dict[str, Any]:
    subsets: List[dict] = []
    if pod_names:
        subsets = [
            {
                "addresses": [
                    {
                        "ip": f"10.244.0.{i + 2}",
                        "targetRef": {"kind": "Pod", "name": p},
                    }
                    for i, p in enumerate(pod_names)
                ],
                "ports": [{"port": port, "protocol": "TCP"}],
            }
        ]
    return {"metadata": meta(name, namespace), "subsets": subsets}


def make_event(
    namespace: str,
    kind: str,
    obj_name: str,
    reason: str,
    message: str,
    etype: str = "Warning",
    count: int = 1,
    source_component: str = "kubelet",
) -> Dict[str, Any]:
    digest = hashlib.sha1(
        f"{obj_name}/{reason}/{message}".encode()
    ).hexdigest()[:12]
    return {
        "metadata": meta(f"{obj_name}.{digest}", namespace),
        "involvedObject": {"kind": kind, "name": obj_name, "namespace": namespace},
        "type": etype,
        "reason": reason,
        "message": message,
        "count": count,
        "source": {"component": source_component},
        "firstTimestamp": MOCK_TIME,
        "lastTimestamp": MOCK_TIME,
    }


def make_hpa(
    name: str,
    namespace: str,
    target: str,
    min_replicas: int,
    max_replicas: int,
    current_replicas: int,
    desired_replicas: int,
    current_cpu_pct: Optional[int] = None,
    target_cpu_pct: int = 80,
) -> Dict[str, Any]:
    return {
        "metadata": meta(name, namespace),
        "spec": {
            "scaleTargetRef": {"kind": "Deployment", "name": target},
            "minReplicas": min_replicas,
            "maxReplicas": max_replicas,
            "targetCPUUtilizationPercentage": target_cpu_pct,
        },
        "status": {
            "currentReplicas": current_replicas,
            "desiredReplicas": desired_replicas,
            "currentCPUUtilizationPercentage": current_cpu_pct,
        },
    }


def make_network_policy(
    name: str,
    namespace: str,
    pod_selector: dict,
    ingress_from_app: Optional[str] = None,
    policy_types: Optional[List[str]] = None,
) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "podSelector": {"matchLabels": pod_selector},
        "policyTypes": policy_types or ["Ingress"],
    }
    if ingress_from_app is not None:
        spec["ingress"] = [
            {"from": [{"podSelector": {"matchLabels": {"app": ingress_from_app}}}]}
        ]
    return {"metadata": meta(name, namespace), "spec": spec}


def make_ingress(
    name: str, namespace: str, host: str, service: str, port: int = 80,
    tls: bool = False
) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "rules": [
            {
                "host": host,
                "http": {
                    "paths": [
                        {
                            "path": "/",
                            "pathType": "Prefix",
                            "backend": {
                                "service": {
                                    "name": service,
                                    "port": {"number": port},
                                }
                            },
                        }
                    ]
                },
            }
        ]
    }
    if tls:
        spec["tls"] = [{"hosts": [host], "secretName": f"{name}-tls"}]
    return {"metadata": meta(name, namespace), "spec": spec}


def make_configmap(name: str, namespace: str, data: Optional[dict] = None) -> dict:
    return {"metadata": meta(name, namespace), "data": data or {}}


def make_secret(name: str, namespace: str, keys: Optional[List[str]] = None) -> dict:
    return {
        "metadata": meta(name, namespace),
        "type": "Opaque",
        "data": {k: "**REDACTED**" for k in (keys or [])},
    }


def make_node(
    name: str,
    ready: bool = True,
    conditions: Optional[List[dict]] = None,
    cpu_capacity: str = "4",
    memory_capacity: str = "16Gi",
) -> Dict[str, Any]:
    if conditions is None:
        conditions = [
            {"type": "Ready", "status": "True" if ready else "False"},
            {"type": "MemoryPressure", "status": "False"},
            {"type": "DiskPressure", "status": "False"},
            {"type": "NetworkUnavailable", "status": "False"},
        ]
    return {
        "metadata": meta(name, labels={"kubernetes.io/hostname": name}),
        "status": {
            "conditions": conditions,
            "capacity": {"cpu": cpu_capacity, "memory": memory_capacity},
            "allocatable": {"cpu": cpu_capacity, "memory": memory_capacity},
            "nodeInfo": {"kubeletVersion": "v1.30.0"},
        },
    }


def pod_metric(
    cpu_millicores: float,
    memory_mib: float,
    cpu_limit_millicores: Optional[float] = None,
    memory_limit_mib: Optional[float] = None,
    container: str = "main",
) -> Dict[str, Any]:
    """Per-pod usage record in the shape the metrics agent consumes.

    Mirrors the reference's ``kubectl top``-derived structure with
    ``usage_percentage`` computed against container limits
    (reference: utils/k8s_client.py:520-546).
    """
    rec: Dict[str, Any] = {
        "cpu": {"usage": f"{int(cpu_millicores)}m"},
        "memory": {"usage": f"{int(memory_mib)}Mi"},
        "containers": {},
    }
    if cpu_limit_millicores:
        rec["cpu"]["usage_percentage"] = round(
            100.0 * cpu_millicores / cpu_limit_millicores, 2
        )
    if memory_limit_mib:
        rec["memory"]["usage_percentage"] = round(
            100.0 * memory_mib / memory_limit_mib, 2
        )
    rec["containers"][container] = {
        "cpu": dict(rec["cpu"]),
        "memory": dict(rec["memory"]),
    }
    return rec
