"""Columnar world state: vectorized capture for 100k-1M-pod clusters.

Until ISSUE 10, every snapshot capture re-walked the namespace one dict at
a time — ``sanitize_objects`` over four 10k-object collections plus the
per-pod ``_pod_feature_row`` Python loop put a full sweep at ~0.5-0.8 s at
10k pods, which extrapolates to tens of seconds per resync at the
100k-1M-pod scale the ROADMAP north star targets (the data-center-scale
graph-construction direction of PAPERS.md [3]).  This module turns that
O(objects) per-sweep cost into O(dirty rows) per MUTATION plus O(1)
vectorized slices per sweep:

- a :class:`ColumnarWorld` **master** binds to one namespace of a mock
  :class:`~rca_tpu.cluster.world.World` and consumes its mutation journal
  (the same feed ``watch_changes`` serves): each journal entry becomes a
  **row write** — the touched object is sanitized once, its derived
  feature fields are encoded once (``_pod_feature_row``, the log-pattern
  scan, the metric percentages — THE same scalar encoders the dict path
  runs, so bit-parity holds by construction), and a dirty-row bitmap
  marks what changed;
- a **mirror** (``mode="mirror"``) holds the same tables on the consumer
  side of the client boundary, fed by :meth:`payload` dicts — a full
  table dump once, then **column diffs** (ordered row ops) from a cursor.
  Record/replay compose naturally: the payloads are what the flight
  recorder logs (``coldiff`` frames, REPLAY.md) instead of re-recording
  whole object lists every sweep, and a replayed mirror reconstructs
  byte-identical tables;
- :meth:`build_view` assembles the extractor's inputs — the packed pod
  feature matrix, the pod->service membership COO pairs, the pod->node
  index — as vectorized slices over the columns (no per-pod Python; the
  ``no-dict-scan`` lint rule keeps it that way).

Contract: mutations must be journal-mediated (``World.touch`` /
``World.add``), the same visibility rule the watch feed already has —
out-of-band dict edits are invisible to both until touched.  Worlds with
duplicate object names in one store are degenerate for name-keyed
maintenance; ``payload`` reports ``supported: False`` and capture falls
back to the dict scans.  Bit-parity of the columnar-vs-dict
:class:`~rca_tpu.features.extract.FeatureSet` is property-tested across
update/delete/NaN/gone-storm sequences (tests/test_columnar.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from rca_tpu.cluster.sanitize import sanitize_objects
from rca_tpu.cluster.world import World
from rca_tpu.features.logscan import LOG_PATTERN_NAMES, scan_text_cached
from rca_tpu.features.schema import NUM_POD_FEATURES, PodF

N_LOG = len(LOG_PATTERN_NAMES)

#: namespaced object stores carried as columnar kind tables, in the
#: (stable) order the payload serializes them.  Events and nodes ride
#: separately (append-only diffs / cluster-scoped wholesale).
KIND_STORES: Tuple[str, ...] = (
    "pods", "services", "deployments", "statefulsets", "daemonsets",
    "cronjobs", "endpoints", "ingresses", "network_policies",
    "configmaps", "secrets", "pvcs", "resource_quotas", "hpas",
)

#: fixed log-tail policy the columns are encoded under — the same
#: ``tail_lines`` the dict capture path passes; a capture asking for a
#: different tail cannot use the columnar path (snapshot.py guards)
LOG_TAIL_LINES = 200

#: retained column-diff ops before old cursors are answered with a full
#: payload instead (mirrors the world journal's expire semantics)
OP_LOG_CAP = 10_000


class ColumnarUnsupported(Exception):
    """The world cannot be maintained columnar (duplicate names)."""


def _tail(text: str, lines: int = LOG_TAIL_LINES) -> str:
    """The mock client's tail_lines semantics, verbatim."""
    if lines <= 0:
        return ""
    return "\n".join(text.splitlines()[-lines:])


def _pod_base_row(pod: dict) -> np.ndarray:
    """The pod-OBJECT-derived feature block: ``_pod_feature_row`` with
    zeroed sidecars (metrics/events/logs ride in their own columns and
    are overlaid vectorized at assembly).  One row definition for both
    paths — this is what makes columnar-vs-dict bit-parity structural."""
    from rca_tpu.features.extract import _pod_feature_row

    return _pod_feature_row(pod, 0, None, None)


def _pod_log_fields(pod: dict, texts_by_container: Dict[str, str],
                    ) -> Tuple[np.ndarray, bool]:
    """(pattern counts int32 [13], any-nonblank flag) for one pod, from
    the world's log store — the same per-container tail-200 view
    ``get_pod_logs`` serves the dict capture."""
    counts = np.zeros(N_LOG, dtype=np.int32)
    nonblank = False
    for c in (pod.get("spec", {}) or {}).get("containers", []) or []:
        text = _tail(texts_by_container.get(c.get("name", ""), "") or "")
        if text:
            counts += scan_text_cached(text)
            nonblank = nonblank or bool(text.strip())
    return counts, nonblank


def _metric_pcts_pair(rec: Optional[dict]) -> Tuple[float, float]:
    from rca_tpu.features.extract import _metric_pcts

    return _metric_pcts(rec)


def _extract_columnar(obj_s: dict, rec: Optional[dict],
                      texts_by_container: Dict[str, str]) -> dict:
    """ONE (sanitized) pod object + its metric record + its log texts ->
    the pod's full columnar scalar block, as the wire-op dict shape.  THE
    shared encoder: the journal-fed master (``_encode_pod_op``), the full
    rebuild, and the live ``K8sApiClient`` adapter (cluster/
    live_columnar.py) all route through here, which is what makes
    live-vs-mock-vs-dict bit-parity structural rather than aspirational."""
    logc, lnb = _pod_log_fields(obj_s, texts_by_container or {})
    return {
        "obj": obj_s, "rec": rec,
        "logc": [int(x) for x in logc], "lnb": bool(lnb),
    }


def _warn_counts_of(events: List[dict]) -> Dict[str, int]:
    """Warning-event counts by involved pod — the extractor's
    ``_warn_counts`` over a plain event list."""
    out: Dict[str, int] = {}
    for ev in events:
        if ev.get("type") == "Normal":
            continue
        obj = ev.get("involvedObject", {}) or {}
        if obj.get("kind") == "Pod":
            name = obj.get("name", "")
            out[name] = out.get(name, 0) + int(ev.get("count", 1) or 1)
    return out


@dataclasses.dataclass
class ColumnarView:
    """Frozen per-capture bundle of the extractor's vectorized inputs.
    Attached to a :class:`~rca_tpu.cluster.snapshot.ClusterSnapshot` as
    ``snapshot.columnar``; every array is materialized at capture time so
    later world mutation cannot drift a retained snapshot."""

    pod_names: List[str]
    pod_features: np.ndarray       # [P, NUM_POD_FEATURES] float32
    pod_service: np.ndarray        # [P] int32
    memb_pod: np.ndarray           # [M] int32
    memb_svc: np.ndarray           # [M] int32
    pod_node: np.ndarray           # [P] int32
    service_names: List[str]
    selectors: List[dict]
    node_names: List[str]
    sampled_names: List[str]       # pods the log policy selected


class _KindTable:
    """One namespaced store as (objects list, name->row index): row order
    mirrors the store list (appends at the end, deletes shift up) so the
    snapshot's object lists stay order-identical to the dict path's."""

    def __init__(self) -> None:
        self.objects: List[dict] = []
        self.pos: Dict[str, int] = {}
        self.rv: List[Optional[str]] = []

    def reset(self, objects: List[dict]) -> None:
        self.objects = list(objects)
        self.pos = {
            (o.get("metadata") or {}).get("name", ""): i
            for i, o in enumerate(self.objects)
        }
        self.rv = [
            (o.get("metadata") or {}).get("resourceVersion")
            for o in self.objects
        ]

    def set(self, name: str, obj: dict) -> int:
        """Upsert; returns the row index."""
        row = self.pos.get(name)
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        if row is None:
            row = len(self.objects)
            self.objects.append(obj)
            self.pos[name] = row
            self.rv.append(rv)
        else:
            self.objects[row] = obj
            self.rv[row] = rv
        return row

    def delete(self, name: str) -> Optional[int]:
        row = self.pos.pop(name, None)
        if row is None:
            return None
        del self.objects[row]
        del self.rv[row]
        for n, i in self.pos.items():
            if i > row:
                self.pos[n] = i - 1
        return row


class _PodColumns:
    """The pod table's numpy columns (amortized-growth capacity arrays).
    Row i aligns with ``_KindTable.objects[i]`` of the pods table."""

    def __init__(self) -> None:
        self.n = 0
        cap = 64
        self.base = np.zeros((cap, NUM_POD_FEATURES), np.float32)
        self.cpu = np.zeros(cap, np.float32)
        self.mem = np.zeros(cap, np.float32)
        self.warn = np.zeros(cap, np.int64)
        self.logc = np.zeros((cap, N_LOG), np.int32)
        self.lnb = np.zeros(cap, bool)
        self.label_sig = np.zeros(cap, np.int32)
        self.node_id = np.full(cap, -1, np.int32)
        # dirty-row bitmap: rows written since the last build_view —
        # observability for tests/bench (the view itself is assembled
        # from full column slices, which is cheaper than gather at the
        # densities a busy tick sees)
        self.dirty = np.zeros(cap, bool)

    def _grow(self, need: int) -> None:
        cap = len(self.cpu)
        if need <= cap:
            return
        new_cap = max(need, cap * 2)

        def grown(a: np.ndarray) -> np.ndarray:
            shape = (new_cap,) + a.shape[1:]
            out = np.zeros(shape, a.dtype)
            out[: self.n] = a[: self.n]
            return out

        self.base = grown(self.base)
        self.cpu = grown(self.cpu)
        self.mem = grown(self.mem)
        self.warn = grown(self.warn)
        self.logc = grown(self.logc)
        self.lnb = grown(self.lnb)
        self.label_sig = grown(self.label_sig)
        node = np.full(new_cap, -1, np.int32)
        node[: self.n] = self.node_id[: self.n]
        self.node_id = node
        self.dirty = grown(self.dirty)

    def ensure_row(self, row: int) -> None:
        if row >= self.n:
            self._grow(row + 1)
            self.n = row + 1

    def delete_rows(self, rows: List[int]) -> None:
        if not rows:
            return
        keep = np.ones(self.n, bool)
        keep[np.asarray(rows, np.int64)] = False
        m = int(keep.sum())
        for attr in ("base", "cpu", "mem", "warn", "logc", "lnb",
                     "label_sig", "node_id", "dirty"):
            a = getattr(self, attr)
            a[:m] = a[: self.n][keep]
            if attr == "node_id":
                a[m: self.n] = -1
            else:
                a[m: self.n] = 0
        self.n = m


class ColumnarWorld:
    """Columnar tables for ONE namespace — master (bound to a World,
    journal-fed) or mirror (payload-fed, the client-side twin)."""

    def __init__(self, namespace: str, world: Optional[World] = None):
        self.namespace = namespace
        self.world = world                      # None = mirror mode
        self.kinds: Dict[str, _KindTable] = {
            k: _KindTable() for k in KIND_STORES
        }
        self.cols = _PodColumns()
        self.events: List[dict] = []
        self.nodes: List[dict] = []
        self.metric_recs: Dict[str, Any] = {}
        self.warn_by_name: Dict[str, int] = {}
        # label-set / node-name registries (append-only; row columns hold
        # int ids into them so membership matching runs per DISTINCT set)
        self.label_registry: List[tuple] = []
        self.label_index: Dict[tuple, int] = {}
        self.node_registry: List[str] = []
        self.node_index: Dict[str, int] = {}
        # master cursor + column-diff op log
        self.cursor: Optional[int] = None
        self._op_log: List[Tuple[int, List[dict]]] = []
        self._op_floor: int = 0
        self._ops_retained = 0
        # selector/membership memo (svc_gen bumps on services mutation)
        self._svc_gen = 0
        self._svc_state: Optional[Dict[str, Any]] = None

    # -- master construction ------------------------------------------------
    @classmethod
    def master(cls, world: World, namespace: str) -> "ColumnarWorld":
        return cls(namespace, world=world)

    def _degenerate(self) -> bool:
        w = self.world
        return any(
            w.store_degenerate(k, self.namespace) for k in KIND_STORES
        )

    # -- encode (master side: world object -> row op) -----------------------
    def _encode_pod_op(self, name: str) -> Optional[dict]:
        w, ns = self.world, self.namespace
        obj = w.find("pods", ns, name)
        if obj is None or not isinstance(obj, dict):
            if name in self.kinds["pods"].pos:
                return {"op": "delpod", "name": name}
            return None
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        tbl = self.kinds["pods"]
        row = tbl.pos.get(name)
        if row is not None and rv is not None and tbl.rv[row] == rv:
            return None  # duplicate journal entry for an already-encoded rv
        clean = sanitize_objects([obj])
        if not clean:
            return None
        obj_s = clean[0]
        rec = (
            w.pod_metrics.get(ns, {}).get("pods", {}) or {}
        ).get(name)
        ext = _extract_columnar(
            obj_s, rec, w.logs.get(ns, {}).get(name, {}) or {}
        )
        return {"op": "pod", "name": name, **ext}

    def _encode_kind_op(self, store: str, name: str) -> Optional[dict]:
        w, ns = self.world, self.namespace
        obj = w.find(store, ns, name)
        if obj is None or not isinstance(obj, dict):
            if name in self.kinds[store].pos:
                return {"op": "del", "kind": store, "name": name}
            return None
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        tbl = self.kinds[store]
        row = tbl.pos.get(name)
        if row is not None and rv is not None and tbl.rv[row] == rv:
            return None
        clean = sanitize_objects([obj])
        if not clean:
            return None
        return {"op": "set", "kind": store, "name": name, "obj": clean[0]}

    def _encode_entries(self, entries: List[dict]) -> List[dict]:
        """Journal entries -> ordered column-diff ops.  Entries process in
        journal order so table row order tracks store list order (deletes
        shift, re-adds append) — the rv skip makes repeats free."""
        ops: List[dict] = []
        events_dirty = False
        nodes_dirty = False
        w, ns = self.world, self.namespace
        plural = World._KIND_PLURAL
        for e in entries:
            if e.get("namespace") != ns and e.get("kind") != "node":
                continue
            kind = e.get("kind", "")
            name = e.get("name", "")
            if kind == "pod":
                op = self._encode_pod_op(name)
                if op:
                    ops.append(op)
            elif kind == "logs":
                obj = self.kinds["pods"].pos.get(name)
                pod = w.find("pods", ns, name)
                if obj is not None and pod is not None:
                    clean = sanitize_objects([pod])
                    if clean:
                        logc, lnb = _pod_log_fields(
                            clean[0], w.logs.get(ns, {}).get(name, {}) or {}
                        )
                        ops.append({
                            "op": "logs", "name": name,
                            "logc": [int(x) for x in logc],
                            "lnb": bool(lnb),
                        })
            elif kind == "pod_metrics":
                rec = (
                    w.pod_metrics.get(ns, {}).get("pods", {}) or {}
                ).get(name)
                ops.append({"op": "metrics", "name": name, "rec": rec})
            elif kind == "event":
                events_dirty = True
            elif kind == "node":
                nodes_dirty = True
            elif kind == "traces":
                continue  # traces ride the snapshot, not the tables
            else:
                store = plural.get(kind)
                if store and store in self.kinds and store != "pods":
                    op = self._encode_kind_op(store, name)
                    if op:
                        ops.append(op)
        if events_dirty:
            cur = self.world.events.get(ns, [])
            known = len(self.events)
            if len(cur) > known:
                ops.append({
                    "op": "events",
                    "append": sanitize_objects(cur[known:]),
                })
            else:
                # shrink or in-place edit: re-sanitize wholesale (events
                # are small next to pods; append-only is the common case)
                ops.append({"op": "events", "full": sanitize_objects(cur)})
        if nodes_dirty:
            ops.append({
                "op": "nodes", "objects": sanitize_objects(self.world.nodes),
            })
        return ops

    # -- refresh (master): drain the world journal --------------------------
    def refresh(self) -> None:
        w = self.world
        if self.cursor is None:
            self._rebuild()
            return
        entries = w.changes_since(self.cursor)
        if entries is None:
            # journal trimmed past our cursor (gone storm): rebuild; old
            # consumer cursors get a full payload
            self._rebuild()
            return
        if not entries:
            return
        ops = self._encode_entries(entries)
        self.cursor = int(entries[-1]["seq"])
        if ops:
            self._apply_ops(ops)
            self._op_log.append((self.cursor, ops))
            self._ops_retained += len(ops)
            while self._op_log and self._ops_retained > OP_LOG_CAP:
                seq, dropped = self._op_log.pop(0)
                self._ops_retained -= len(dropped)
                self._op_floor = seq

    def _rebuild(self) -> None:
        """Full rebuild from the world's stores (initialization, or
        journal-expiry recovery — the columnar analogue of a resync)."""
        w, ns = self.world, self.namespace
        self.cursor = int(w.journal_seq)
        self._op_log = []
        self._ops_retained = 0
        self._op_floor = self.cursor
        self.events = sanitize_objects(w.events.get(ns, []))
        self.nodes = sanitize_objects(w.nodes)
        self.warn_by_name = _warn_counts_of(self.events)
        self.metric_recs = dict(
            w.pod_metrics.get(ns, {}).get("pods", {}) or {}
        )
        for store, tbl in self.kinds.items():
            if store == "pods":
                continue
            tbl.reset(sanitize_objects(
                getattr(w, store).get(ns, [])
            ))
        self._svc_gen += 1
        pods = sanitize_objects(w.pods.get(ns, []))
        self.kinds["pods"].reset(pods)
        self.cols = _PodColumns()
        self.cols._grow(len(pods))
        self.cols.n = len(pods)
        logs_store = w.logs.get(ns, {})
        for i, pod in enumerate(pods):
            name = (pod.get("metadata") or {}).get("name", "")
            rec = self.metric_recs.get(name)
            ext = _extract_columnar(pod, rec, logs_store.get(name, {}) or {})
            self._write_pod_row(i, pod, rec, ext["logc"], ext["lnb"])

    # -- shared row write (master + mirror) ---------------------------------
    def _label_sig(self, labels: Dict[str, str]) -> int:
        key = tuple(sorted(labels.items()))
        sig = self.label_index.get(key)
        if sig is None:
            sig = len(self.label_registry)
            self.label_registry.append(key)
            self.label_index[key] = sig
        return sig

    def _node_sig(self, node: Any) -> int:
        if not node:
            return -1
        sig = self.node_index.get(node)
        if sig is None:
            sig = len(self.node_registry)
            self.node_registry.append(node)
            self.node_index[node] = sig
        return sig

    def _write_pod_row(self, row: int, obj: dict, rec: Optional[dict],
                       logc: Any, lnb: bool) -> None:
        c = self.cols
        c.ensure_row(row)
        c.base[row] = _pod_base_row(obj)
        cpu, mem = _metric_pcts_pair(rec)
        c.cpu[row] = cpu
        c.mem[row] = mem
        md = obj.get("metadata") or {}
        name = md.get("name", "")
        c.warn[row] = self.warn_by_name.get(name, 0)
        c.logc[row] = np.asarray(logc, np.int32)
        c.lnb[row] = bool(lnb)
        c.label_sig[row] = self._label_sig(md.get("labels", {}) or {})
        c.node_id[row] = self._node_sig(
            (obj.get("spec", {}) or {}).get("nodeName")
        )
        c.dirty[row] = True

    def _apply_events(self, op: dict) -> None:
        pos = self.kinds["pods"].pos
        if "append" in op:
            new = list(op["append"])
            self.events.extend(new)
            delta = _warn_counts_of(new)
            for name, cnt in delta.items():
                self.warn_by_name[name] = (
                    self.warn_by_name.get(name, 0) + cnt
                )
            touched = list(delta)
        else:
            self.events = list(op["full"])
            self.warn_by_name = _warn_counts_of(self.events)
            # full recompute: pods whose events disappeared must zero too
            touched = list(pos)
        for name in touched:
            row = pos.get(name)
            if row is not None:
                self.cols.warn[row] = self.warn_by_name.get(name, 0)
                self.cols.dirty[row] = True

    def _apply_ops(self, ops: List[dict]) -> None:
        i = 0
        pods = self.kinds["pods"]
        while i < len(ops):
            op = ops[i]
            k = op["op"]
            if k == "delpod":
                # table delete shifts later rows up; the column compaction
                # uses the row index valid at that same moment
                row = pods.delete(op["name"])
                if row is not None:
                    self.cols.delete_rows([row])
                i += 1
                continue
            if k == "pod":
                obj = op["obj"]
                row = pods.set(op["name"], obj)
                rec = op.get("rec")
                if rec is not None:
                    self.metric_recs[op["name"]] = rec
                else:
                    self.metric_recs.pop(op["name"], None)
                self._write_pod_row(
                    row, obj, rec, op["logc"], op["lnb"]
                )
            elif k == "logs":
                row = pods.pos.get(op["name"])
                if row is not None:
                    self.cols.logc[row] = np.asarray(op["logc"], np.int32)
                    self.cols.lnb[row] = bool(op["lnb"])
                    self.cols.dirty[row] = True
            elif k == "metrics":
                rec = op.get("rec")
                name = op["name"]
                if rec is not None:
                    self.metric_recs[name] = rec
                else:
                    self.metric_recs.pop(name, None)
                row = pods.pos.get(name)
                if row is not None:
                    cpu, mem = _metric_pcts_pair(rec)
                    self.cols.cpu[row] = cpu
                    self.cols.mem[row] = mem
                    self.cols.dirty[row] = True
            elif k == "set":
                self.kinds[op["kind"]].set(op["name"], op["obj"])
                if op["kind"] == "services":
                    self._svc_gen += 1
            elif k == "del":
                self.kinds[op["kind"]].delete(op["name"])
                if op["kind"] == "services":
                    self._svc_gen += 1
            elif k == "events":
                self._apply_events(op)
            elif k == "nodes":
                self.nodes = list(op["objects"])
            i += 1

    # -- payload (master serves; mirror applies) ----------------------------
    def payload(self, cursor: Optional[str] = None) -> Dict[str, Any]:
        """Full table dump (``cursor`` None/expired) or the column-diff
        ops since ``cursor``.  The wire shape is JSON-able except the
        full dump's numpy columns — the recorder tags/encodes those
        (``coldiff`` frames)."""
        if self._degenerate():
            return {"supported": False, "reason": "duplicate object names"}
        self.refresh()
        cur: Optional[int] = None
        if cursor is not None:
            try:
                cur = int(cursor)
            except (TypeError, ValueError):
                cur = None
        if cur is not None and self._op_floor <= cur <= self.cursor:
            ops: List[dict] = []
            for seq, batch in self._op_log:
                if seq > cur:
                    ops.extend(batch)
            return {
                "supported": True, "full": False,
                "cursor": str(self.cursor), "ops": ops,
            }
        n = self.cols.n
        return {
            "supported": True, "full": True, "cursor": str(self.cursor),
            "kinds": {
                k: list(t.objects) for k, t in self.kinds.items()
            },
            "events": list(self.events),
            "nodes": list(self.nodes),
            "pods_aux": {
                "metrics": dict(self.metric_recs),
                "base": self.cols.base[:n],
                "cpu": self.cols.cpu[:n],
                "mem": self.cols.mem[:n],
                "warn": self.cols.warn[:n],
                "logc": self.cols.logc[:n],
                "lnb": self.cols.lnb[:n],
                "label_sig": self.cols.label_sig[:n],
                "node_id": self.cols.node_id[:n],
                "label_sets": [list(map(list, t))
                               for t in self.label_registry],
                "node_names": list(self.node_registry),
            },
        }

    # -- mirror: apply a payload -------------------------------------------
    def apply_payload(self, payload: Dict[str, Any]
                      ) -> Tuple[bool, Set[str], Set[str]]:
        """Apply one :meth:`payload` to mirror tables; returns
        ``(full, changed_pod_names, removed_pod_names)`` so the capture
        layer knows which log-text cache entries went stale."""
        if not payload.get("supported"):
            raise ColumnarUnsupported(payload.get("reason", ""))
        raw = payload.get("cursor")
        self.cursor = int(raw) if raw is not None else None
        if payload.get("full"):
            self._reset_from_full(payload)
            return True, set(), set()
        changed: Set[str] = set()
        removed: Set[str] = set()
        ops = payload.get("ops", [])
        for op in ops:
            k = op["op"]
            if k in ("pod", "logs"):
                changed.add(op["name"])
            elif k == "delpod":
                removed.add(op["name"])
        self._apply_ops(ops)
        return False, changed, removed

    def _reset_from_full(self, payload: Dict[str, Any]) -> None:
        for k, tbl in self.kinds.items():
            tbl.reset(payload["kinds"].get(k, []))
        self._svc_gen += 1
        self.events = list(payload.get("events", []))
        self.nodes = list(payload.get("nodes", []))
        self.warn_by_name = _warn_counts_of(self.events)
        aux = payload["pods_aux"]
        self.metric_recs = dict(aux.get("metrics", {}))
        n = len(self.kinds["pods"].objects)
        cols = _PodColumns()
        cols._grow(n)
        cols.n = n
        cols.base[:n] = np.asarray(aux["base"], np.float32)
        cols.cpu[:n] = np.asarray(aux["cpu"], np.float32)
        cols.mem[:n] = np.asarray(aux["mem"], np.float32)
        cols.warn[:n] = np.asarray(aux["warn"], np.int64)
        cols.logc[:n] = np.asarray(aux["logc"], np.int32)
        cols.lnb[:n] = np.asarray(aux["lnb"], bool)
        cols.label_sig[:n] = np.asarray(aux["label_sig"], np.int32)
        cols.node_id[:n] = np.asarray(aux["node_id"], np.int32)
        cols.dirty[:n] = True
        self.cols = cols
        self.label_registry = [
            tuple(tuple(kv) for kv in entry)
            for entry in aux.get("label_sets", [])
        ]
        self.label_index = {
            t: i for i, t in enumerate(self.label_registry)
        }
        self.node_registry = list(aux.get("node_names", []))
        self.node_index = {
            t: i for i, t in enumerate(self.node_registry)
        }

    # -- vectorized assembly (the extractor's fast path) --------------------
    def _selector_state(self) -> Dict[str, Any]:
        """Service names/selectors + per-distinct-label-set match lists,
        memoized across captures (selectors invalidate on any services
        mutation; the hits list only ever EXTENDS for new label sets)."""
        from rca_tpu.cluster.labels import SelectorIndex

        st = self._svc_state
        if st is None or st["gen"] != self._svc_gen:
            services = self.kinds["services"].objects
            service_names = [
                s.get("metadata", {}).get("name", f"svc-{j}")
                for j, s in enumerate(services)
            ]
            selectors = [
                (s.get("spec", {}) or {}).get("selector") or {}
                for s in services
            ]
            st = {
                "gen": self._svc_gen,
                "names": service_names,
                "selectors": selectors,
                "index": SelectorIndex(selectors),
                "hits": [],
            }
            self._svc_state = st
        hits: List[np.ndarray] = st["hits"]
        while len(hits) < len(self.label_registry):
            items = self.label_registry[len(hits)]
            hits.append(np.asarray(
                st["index"].matches(dict(items)), np.int32
            ))
        return st

    def _sampled_mask(self, max_log_pods: Optional[int]) -> np.ndarray:
        """[no-dict-scan] The log-fetch priority policy
        (``_prioritize_pods_for_logs``) as a vectorized mask: all
        unhealthy pods, then healthy ones up to the cap, in pod order."""
        n = self.cols.n
        b = self.cols.base[:n]
        healthy = (
            ((b[:, PodF.PHASE_RUNNING] == 1.0)
             | (b[:, PodF.PHASE_SUCCEEDED] == 1.0))
            & (b[:, PodF.NOT_READY] == 0.0)
            & (b[:, PodF.RESTARTS] == 0.0)
        )
        uidx = np.flatnonzero(~healthy)
        hidx = np.flatnonzero(healthy)
        if max_log_pods is None:
            sel = np.concatenate([uidx, hidx[:25]])
        else:
            sel = np.concatenate([uidx, hidx])[:max_log_pods]
        mask = np.zeros(n, bool)
        mask[sel] = True
        return mask

    def build_view(self, max_log_pods: Optional[int] = None) -> ColumnarView:
        """[no-dict-scan] Assemble the extractor's inputs as vectorized
        slices over the columns — the whole per-capture cost is a few
        array copies; no per-pod Python runs here."""
        c = self.cols
        n = c.n
        feat = c.base[:n].copy()
        feat[:, PodF.CPU_PCT] = c.cpu[:n]
        feat[:, PodF.MEM_PCT] = c.mem[:n]
        w = c.warn[:n]
        feat[:, PodF.WARN_EVENTS] = w
        feat[:, PodF.WARN_EVENTS_SAT] = np.minimum(1.0, w / 10.0)
        sampled = self._sampled_mask(max_log_pods)
        if sampled.any():
            feat[sampled, PodF.LOG0: PodF.LOG0 + N_LOG] = (
                c.logc[:n][sampled].astype(np.float32)
            )
            silent = (
                sampled
                & (c.base[:n, PodF.PHASE_RUNNING] == 1.0)
                & ~c.lnb[:n]
            )
            feat[silent, PodF.NO_LOGS] = 1.0
        c.dirty[:n] = False

        st = self._selector_state()
        hits: List[np.ndarray] = st["hits"]
        sig = c.label_sig[:n]
        if hits:
            lens = np.asarray([len(h) for h in hits], np.int64)
            flat = (
                np.concatenate(hits) if lens.sum()
                else np.zeros(0, np.int32)
            )
            offs = np.concatenate([[0], np.cumsum(lens)])[:-1]
            firsts = np.asarray(
                [int(h[0]) if len(h) else -1 for h in hits], np.int32
            )
            counts = lens[sig]
            total = int(counts.sum())
            memb_pod = np.repeat(
                np.arange(n, dtype=np.int64), counts
            ).astype(np.int32)
            starts = np.repeat(offs[sig], counts)
            within = (
                np.arange(total, dtype=np.int64)
                - np.repeat(np.cumsum(counts) - counts, counts)
            )
            memb_svc = (
                flat[starts + within].astype(np.int32) if total
                else np.zeros(0, np.int32)
            )
            pod_service = np.where(
                counts > 0, firsts[sig], np.int32(-1)
            ).astype(np.int32)
        else:
            memb_pod = np.zeros(0, np.int32)
            memb_svc = np.zeros(0, np.int32)
            pod_service = np.full(n, -1, np.int32)

        node_names = [
            nd.get("metadata", {}).get("name", "") for nd in self.nodes
        ]
        node_pos = {name: i for i, name in enumerate(node_names)}
        lut = np.asarray(
            [node_pos.get(nm, -1) for nm in self.node_registry] or [-1],
            np.int32,
        )
        nid = c.node_id[:n]
        pod_node = np.where(
            nid >= 0, lut[np.clip(nid, 0, None)], np.int32(-1)
        ).astype(np.int32)

        names = self.kinds["pods"].objects
        pod_names = [
            p.get("metadata", {}).get("name", f"pod-{i}")
            for i, p in enumerate(names)
        ]
        sampled_names = [pod_names[i] for i in np.flatnonzero(sampled)]
        return ColumnarView(
            pod_names=pod_names,
            pod_features=feat,
            pod_service=pod_service,
            memb_pod=memb_pod,
            memb_svc=memb_svc,
            pod_node=pod_node,
            service_names=list(st["names"]),
            selectors=list(st["selectors"]),
            node_names=node_names,
            sampled_names=sampled_names,
        )


class ColumnarClientState:
    """The consumer-side columnar session state a capture loop carries
    across polls: the mirror tables, the feed cursor, and the log-text
    cache (texts refetch only for pods whose rows changed — the same
    refetch-on-journal contract the dict patch path has)."""

    def __init__(self) -> None:
        self.tables: Optional[ColumnarWorld] = None
        self.log_texts: Dict[str, Dict[str, str]] = {}

    @property
    def cursor(self) -> Optional[str]:
        if self.tables is None or self.tables.cursor is None:
            return None
        return str(self.tables.cursor)

    def apply(self, namespace: str, payload: Dict[str, Any]
              ) -> Tuple[bool, Set[str], Set[str]]:
        if self.tables is None:
            self.tables = ColumnarWorld(namespace)
        full, changed, removed = self.tables.apply_payload(payload)
        if full:
            self.log_texts.clear()
        else:
            for name in changed:
                self.log_texts.pop(name, None)
            for name in removed:
                self.log_texts.pop(name, None)
        return full, changed, removed
