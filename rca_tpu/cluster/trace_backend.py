"""Jaeger-HTTP trace backend: a LIVE signal behind the protocol's trace
methods.

Traces were the reference's weakest signal: its trace data existed only on
the mock client (reference: utils/mock_k8s_client.py:1146-1303 — canned
trace ids, latency stats, error rates, dependencies), and its live client
had no trace methods at all; its traces agent's latency/error analyses
were simulated stubs (reference: agents/traces_agent.py:209-381).  This
module makes the live path real: point ``RCA_TRACE_ENDPOINT`` at a Jaeger
query service (``http://jaeger-query:16686``) and
:class:`rca_tpu.cluster.k8s_client.K8sApiClient` serves the SAME
structures the mock does — the traces agent, the feature extractor's
error-rate/latency channels, and the trace-derived dependency edges all
light up unchanged (VERDICT r3 item 5).

Only stdlib HTTP (urllib) — no new dependencies; the opener is injectable
so the conformance suite drives the adapter from recorded Jaeger JSON
without a network (tests/test_trace_backend.py).

Jaeger query API used (stable since 1.x):

- ``GET /api/services``                      → {"data": [service names]}
- ``GET /api/traces?service=S&limit=N...``   → {"data": [trace objects]}
- ``GET /api/traces/{trace_id}``             → {"data": [one trace]}
- ``GET /api/dependencies?endTs=ms&lookback=ms`` → {"data": [{parent,
  child, callCount}]}

Derivations (all shapes mirror MockClusterClient):

- latency stats: per-service span-duration percentiles (p50/p95/p99, ms);
- error rate: fraction of a service's spans tagged ``error=true`` or with
  a 5xx ``http.status_code``;
- dependencies: {parent: [children]} from the dependency endpoint;
- slow operations: spans over the threshold, most recent traces first.

Namespaces: Jaeger service names carry no namespace.  The conventional
deployment runs one Jaeger per cluster with services named after their
Kubernetes services, so the adapter serves every service it sees for any
namespace; operators running ``service.namespace`` naming can filter with
``RCA_TRACE_SERVICE_SUFFIX=.<ns>`` (matched and stripped).
"""

from __future__ import annotations

import json
import os
import time
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from rca_tpu.config import env_raw, env_str

DEFAULT_TIMEOUT_S = 5.0
DEFAULT_LOOKBACK_S = 3600
_MS = 1000.0  # Jaeger span times are microseconds


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class JaegerTraceBackend:
    """Read-only adapter over one Jaeger query endpoint."""

    def __init__(
        self,
        endpoint: str,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        lookback_s: int = DEFAULT_LOOKBACK_S,
        opener: Optional[Callable[[str], bytes]] = None,
        service_suffix: str = "",
        trace_limit: int = 40,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s
        self.lookback_s = lookback_s
        self.service_suffix = service_suffix
        self.trace_limit = trace_limit
        self._opener = opener or self._http_get
        # errors surface through the client's degraded-mode channel; the
        # adapter itself never raises into the analysis path
        self.errors: List[str] = []

    # -- transport ----------------------------------------------------------
    def _http_get(self, url: str) -> bytes:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.read()

    def _get(self, path: str, **params: Any) -> Any:
        url = self.endpoint + path
        if params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None}
            )
        try:
            return json.loads(self._opener(url).decode("utf-8"))
        except Exception as exc:
            if len(self.errors) < 20:
                self.errors.append(f"{path}: {type(exc).__name__}: {exc}")
            return None

    # -- raw fetches --------------------------------------------------------
    def _services(self) -> List[str]:
        data = (self._get("/api/services") or {}).get("data") or []
        if self.service_suffix:
            data = [s for s in data if s.endswith(self.service_suffix)]
        return [self._strip(s) for s in data if s]

    def _strip(self, service: str) -> str:
        if self.service_suffix and service.endswith(self.service_suffix):
            return service[: -len(self.service_suffix)]
        return service

    def _traces_for(self, service: str, limit: int) -> List[dict]:
        data = self._get(
            "/api/traces",
            service=service + self.service_suffix,
            limit=limit,
            lookback=f"{self.lookback_s}s",
        )
        return (data or {}).get("data") or []

    @staticmethod
    def _spans_by_service(trace: dict):
        """(service, span) pairs via the trace's process table."""
        procs = {
            pid: (p or {}).get("serviceName", "")
            for pid, p in (trace.get("processes") or {}).items()
        }
        for span in trace.get("spans") or []:
            yield procs.get(span.get("processID", ""), ""), span

    @staticmethod
    def _span_errored(span: dict) -> bool:
        for tag in span.get("tags") or []:
            key, val = tag.get("key"), tag.get("value")
            if key == "error" and val in (True, "true", "True"):
                return True
            if key == "http.status_code":
                try:
                    if int(val) >= 500:
                        return True
                except (TypeError, ValueError):
                    pass
        return False

    def _sample(self) -> Dict[str, List[dict]]:
        """service -> its spans, across a bounded trace sample per service.

        Traces are deduplicated by traceID across the per-service sweep: a
        trace touching services A, B and C comes back from all three
        queries, and counting its spans three times would skew error rates
        and latency percentiles toward widely-shared traces (and emit
        duplicate slow-operation rows)."""
        per_service: Dict[str, List[dict]] = {}
        seen: set = set()
        for svc in self._services():
            for trace in self._traces_for(svc, self.trace_limit):
                tid = trace.get("traceID")
                if tid in seen:
                    continue
                seen.add(tid)
                for sname, span in self._spans_by_service(trace):
                    sname = self._strip(sname)
                    if sname:
                        per_service.setdefault(sname, []).append(span)
        return per_service

    # -- protocol surface (mock-twin shapes) --------------------------------
    def trace_ids(self, namespace: str, limit: int = 20) -> List[str]:
        ids: List[str] = []
        for svc in self._services():
            for trace in self._traces_for(svc, limit):
                tid = trace.get("traceID")
                if tid and tid not in ids:
                    ids.append(tid)
                if len(ids) >= limit:
                    return ids
        return ids

    def trace_details(self, trace_id: str) -> Dict[str, Any]:
        data = self._get(f"/api/traces/{urllib.parse.quote(trace_id)}")
        traces = (data or {}).get("data") or []
        if not traces:
            return {}
        trace = traces[0]
        spans = []
        services = set()
        t0 = None
        t_end = 0.0
        for sname, span in self._spans_by_service(trace):
            sname = self._strip(sname)
            services.add(sname)
            start = float(span.get("startTime", 0) or 0)
            dur = float(span.get("duration", 0) or 0)
            t0 = start if t0 is None else min(t0, start)
            t_end = max(t_end, start + dur)
            spans.append({
                "service": sname,
                "operation": span.get("operationName", ""),
                "duration_ms": round(dur / _MS, 3),
                "error": self._span_errored(span),
            })
        return {
            "trace_id": trace.get("traceID", trace_id),
            "duration_ms": round(max(t_end - (t0 or 0.0), 0.0) / _MS, 3),
            "services": sorted(services),
            "span_count": len(spans),
            "spans": spans,
        }

    def service_latency_stats(self, namespace: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for svc, spans in self._sample().items():
            durs = sorted(
                float(s.get("duration", 0) or 0) / _MS for s in spans
            )
            if durs:
                out[svc] = {
                    "p50": round(_percentile(durs, 0.50), 3),
                    "p95": round(_percentile(durs, 0.95), 3),
                    "p99": round(_percentile(durs, 0.99), 3),
                }
        return out

    def error_rate_by_service(self, namespace: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for svc, spans in self._sample().items():
            if spans:
                errored = sum(1 for s in spans if self._span_errored(s))
                out[svc] = round(errored / len(spans), 4)
        return out

    def service_dependencies(self, namespace: str) -> Dict[str, Any]:
        data = self._get(
            "/api/dependencies",
            endTs=int(time.time() * 1000),
            lookback=self.lookback_s * 1000,
        )
        deps: Dict[str, List[str]] = {}
        for link in (data or {}).get("data") or []:
            parent = self._strip(str(link.get("parent", "")))
            child = self._strip(str(link.get("child", "")))
            if parent and child and parent != child:
                deps.setdefault(parent, [])
                if child not in deps[parent]:
                    deps[parent].append(child)
        return {k: sorted(v) for k, v in deps.items()}

    def find_slow_operations(
        self, namespace: str, threshold_ms: float = 500.0
    ) -> List[Dict[str, Any]]:
        out = []
        for svc, spans in self._sample().items():
            for span in spans:
                dur_ms = float(span.get("duration", 0) or 0) / _MS
                if dur_ms >= threshold_ms:
                    out.append({
                        "service": svc,
                        "operation": span.get("operationName", ""),
                        "duration_ms": round(dur_ms, 3),
                        "trace_id": span.get("traceID", ""),
                    })
        out.sort(key=lambda op: -op["duration_ms"])
        return out


def make_trace_backend() -> Optional[JaegerTraceBackend]:
    """Backend from ``RCA_TRACE_ENDPOINT`` (unset → None, the empty-trace
    behavior the live client always had)."""
    endpoint = env_str("RCA_TRACE_ENDPOINT", "")
    if not endpoint:
        return None
    # accept an explicit scheme prefix ("jaeger:http://...") for future
    # backends; plain URLs mean jaeger
    if endpoint.lower().startswith("jaeger:"):
        endpoint = endpoint[len("jaeger:"):]
    return JaegerTraceBackend(
        endpoint,
        service_suffix=env_raw("RCA_TRACE_SERVICE_SUFFIX", "") or "",
    )
