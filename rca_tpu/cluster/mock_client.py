"""Hermetic mock cluster client backed by a :class:`World`.

Drop-in replacement for the live client (same :class:`ClusterClient`
protocol), playing the role of the reference's ``MockK8sClient``
(reference: utils/mock_k8s_client.py) but parameterized by a programmatic
world so the same code serves the 5-service faulted fixture and the
50/2k/10k/50k-service synthetic configs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from rca_tpu.cluster.world import MOCK_TIME, World
from rca_tpu.findings import utcnow_iso


def _name(obj: dict) -> str:
    return obj.get("metadata", {}).get("name", "")


class MockClusterClient:
    """In-memory :class:`ClusterClient` implementation."""

    def __init__(self, world: World, frozen_time: bool = True):
        self.world = world
        self._frozen_time = frozen_time

    # ---- connection / identity -------------------------------------------
    def is_connected(self) -> bool:
        return True

    def get_current_time(self) -> str:
        # the one wall-clock seam in the mock (nondet-discipline
        # allowlists exactly this function): frozen by default so
        # recorded/replayed captures are host-independent
        return MOCK_TIME if self._frozen_time else utcnow_iso()

    def get_cluster_info(self) -> Dict[str, Any]:
        return {
            "name": self.world.cluster_name,
            "nodes": len(self.world.nodes),
            "namespaces": self.world.namespaces(),
            "errors": [],
            "mock": True,
        }

    def collect_errors(self, clear: bool = True) -> List[Dict[str, str]]:
        return []  # in-memory world: fetches cannot fail

    def get_namespaces(self) -> List[str]:
        return self.world.namespaces()

    # ---- pods ------------------------------------------------------------
    def get_pods(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.pods.get(namespace, []))

    def get_pod(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        for pod in self.world.pods.get(namespace, []):
            if _name(pod) == name:
                return pod
        return None

    def get_pod_logs(
        self,
        namespace: str,
        pod_name: str,
        container: Optional[str] = None,
        previous: bool = False,
        tail_lines: Optional[int] = None,
    ) -> str:
        store = self.world.previous_logs if previous else self.world.logs
        by_container = store.get(namespace, {}).get(pod_name, {})
        if not by_container:
            return ""
        if container is None:
            container = next(iter(by_container))
        text = by_container.get(container, "")
        if tail_lines is not None:
            lines = text.splitlines()[-tail_lines:] if tail_lines > 0 else []
            text = "\n".join(lines)
        return text

    def get_recently_terminated_pods(self, namespace: str) -> List[Dict[str, Any]]:
        out = []
        for pod in self.world.pods.get(namespace, []):
            for cs in pod.get("status", {}).get("containerStatuses", []) or []:
                if "terminated" in (cs.get("state") or {}):
                    out.append(pod)
                    break
        return out

    # ---- workloads -------------------------------------------------------
    def get_deployments(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.deployments.get(namespace, []))

    def get_deployment(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        for d in self.world.deployments.get(namespace, []):
            if _name(d) == name:
                return d
        return None

    def get_statefulsets(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.statefulsets.get(namespace, []))

    def get_daemonsets(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.daemonsets.get(namespace, []))

    def get_cronjobs(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.cronjobs.get(namespace, []))

    # ---- services / networking -------------------------------------------
    def get_services(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.services.get(namespace, []))

    def get_service(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        for s in self.world.services.get(namespace, []):
            if _name(s) == name:
                return s
        return None

    def get_endpoints(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.endpoints.get(namespace, []))

    def get_ingresses(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.ingresses.get(namespace, []))

    def get_network_policies(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.network_policies.get(namespace, []))

    # ---- config / storage ------------------------------------------------
    def get_configmaps(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.configmaps.get(namespace, []))

    def get_secrets(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.secrets.get(namespace, []))

    def get_pvcs(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.pvcs.get(namespace, []))

    def get_pvc(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        for p in self.world.pvcs.get(namespace, []):
            if _name(p) == name:
                return p
        return None

    def get_resource_quotas(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.resource_quotas.get(namespace, []))

    # ---- nodes / metrics / autoscaling -----------------------------------
    def get_nodes(self) -> List[Dict[str, Any]]:
        return list(self.world.nodes)

    def get_node_metrics(self) -> Dict[str, Any]:
        return dict(self.world.node_metrics)

    def get_pod_metrics(self, namespace: str) -> Dict[str, Any]:
        return dict(self.world.pod_metrics.get(namespace, {}))

    def get_hpas(self, namespace: str) -> List[Dict[str, Any]]:
        return list(self.world.hpas.get(namespace, []))

    # ---- events ----------------------------------------------------------
    def get_events(
        self, namespace: str, field_selector: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        events = list(self.world.events.get(namespace, []))
        if not field_selector:
            return events
        # Supports the selector forms the agents actually use
        # (reference: utils/k8s_client.py:606, mcp_events_agent.py:216):
        # "type!=Normal", "type=Warning",
        # "involvedObject.kind=Pod,involvedObject.name=foo"
        for clause in field_selector.split(","):
            clause = clause.strip()
            if "!=" in clause:
                key, val = clause.split("!=", 1)
                events = [e for e in events if str(_field(e, key)) != val]
            elif "=" in clause:
                key, val = clause.split("=", 1)
                events = [e for e in events if str(_field(e, key)) == val]
        return events

    # ---- traces ----------------------------------------------------------
    def get_trace_ids(self, namespace: str, limit: int = 20) -> List[str]:
        ids = self.world.traces.get("trace_ids", {}).get(namespace, [])
        return list(ids)[:limit]

    def get_trace_details(self, trace_id: str) -> Dict[str, Any]:
        return dict(self.world.traces.get("traces", {}).get(trace_id, {}))

    def get_service_latency_stats(self, namespace: str) -> Dict[str, Any]:
        return dict(self.world.traces.get("latency", {}).get(namespace, {}))

    def get_error_rate_by_service(self, namespace: str) -> Dict[str, Any]:
        return dict(self.world.traces.get("error_rates", {}).get(namespace, {}))

    def get_service_dependencies(self, namespace: str) -> Dict[str, Any]:
        return dict(self.world.traces.get("dependencies", {}).get(namespace, {}))

    def find_slow_operations(
        self, namespace: str, threshold_ms: float = 500.0
    ) -> List[Dict[str, Any]]:
        ops = self.world.traces.get("slow_ops", {}).get(namespace, [])
        return [op for op in ops if op.get("duration_ms", 0) >= threshold_ms]

    # ---- columnar capture surface (ISSUE 10) ------------------------------
    def get_columnar(
        self, namespace: str, cursor: Optional[str] = None
    ) -> Dict[str, Any]:
        """Columnar world-state feed: the full table dump on a fresh (or
        expired) cursor, column-diff row ops after.  The journal that
        backs ``watch_changes`` drives the row writes, so the two feeds
        expire together and a recorded session replays both
        deterministically.  ``supported: False`` (degenerate world —
        duplicate object names) sends the caller back to the dict scans."""
        from rca_tpu.cluster.columnar import ColumnarWorld

        master = self.world._columnar.get(namespace)
        if master is None:
            master = ColumnarWorld.master(self.world, namespace)
            self.world._columnar[namespace] = master
        return master.payload(cursor)

    # ---- incremental changes (watch surface) ------------------------------
    def watch_changes(
        self, namespace: str, cursor: Optional[str]
    ) -> Dict[str, Any]:
        """Journal-backed incremental change feed (the hermetic twin of
        kubernetes watch streams; VERDICT r2 item 6).

        ``cursor=None`` opens the feed at the journal head.  Returns
        ``{"supported", "cursor", "expired", "changes"}`` where each change
        is ``{"kind", "name"}`` (deduped, this namespace only).  A cursor
        older than the journal's retained window reports ``expired`` — the
        caller must resync from a full snapshot, exactly like a 410 Gone
        on a real watch."""
        w = self.world
        if cursor is None:
            return {"supported": True, "cursor": str(w.journal_seq),
                    "expired": False, "changes": []}
        try:
            seq = int(cursor)
        except ValueError:
            return {"supported": True, "cursor": str(w.journal_seq),
                    "expired": True, "changes": []}
        entries = w.changes_since(seq)
        if entries is None:
            return {"supported": True, "cursor": str(w.journal_seq),
                    "expired": True, "changes": []}
        by_key = {}
        changes = []
        for e in entries:
            if e["namespace"] != namespace:
                continue
            key = (e["kind"], e["name"])
            rec = by_key.get(key)
            if rec is None:
                # seq doubles as the stamped resourceVersion (touch):
                # row-write consumers key re-encodes on it (ISSUE 10)
                rec = {"kind": e["kind"], "name": e["name"],
                       "rv": str(e["seq"])}
                by_key[key] = rec
                changes.append(rec)
            else:
                rec["rv"] = str(e["seq"])  # dedupe keeps the newest rv
        return {"supported": True, "cursor": str(w.journal_seq),
                "expired": False, "changes": changes}

    # ---- generic ---------------------------------------------------------
    _KIND_STORES = {
        "pod": "pods",
        "deployment": "deployments",
        "statefulset": "statefulsets",
        "daemonset": "daemonsets",
        "cronjob": "cronjobs",
        "service": "services",
        "endpoints": "endpoints",
        "ingress": "ingresses",
        "networkpolicy": "network_policies",
        "configmap": "configmaps",
        "secret": "secrets",
        "persistentvolumeclaim": "pvcs",
        "pvc": "pvcs",
        "resourcequota": "resource_quotas",
        "horizontalpodautoscaler": "hpas",
        "hpa": "hpas",
    }

    def get_resource_details(
        self, namespace: str, kind: str, name: str
    ) -> Dict[str, Any]:
        store_name = self._KIND_STORES.get(kind.lower())
        if store_name is None:
            return {"error": f"unsupported resource kind: {kind}"}
        objects = getattr(self.world, store_name).get(namespace, [])
        match = None
        for obj in objects:
            if _name(obj) == name:
                match = obj
                break
        if match is None:
            for obj in objects:  # prefix fallback after all exact checks
                if _name(obj).startswith(name):
                    match = obj
                    break
        if match is None:
            return {
                "error": f"{kind}/{name} not found in namespace {namespace}"
            }
        # COPY before annotating: the stored world object must not mutate
        from rca_tpu.findings import annotate_created_ago

        return annotate_created_ago(dict(match), self.get_current_time())

    def run_kubectl(self, args: List[str]) -> str:
        """Mock kubectl escape hatch — renders a describe-ish text view."""
        if len(args) >= 3 and args[0] == "describe":
            details = self.get_resource_details(
                _extract_ns(args) or "default", args[1], args[2]
            )
            import json

            return json.dumps(details, indent=2, default=str)
        return f"(mock kubectl) {' '.join(args)}"


def _field(event: dict, dotted_key: str) -> Any:
    cur: Any = event
    for part in dotted_key.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _extract_ns(args: List[str]) -> Optional[str]:
    for i, a in enumerate(args):
        if a in ("-n", "--namespace") and i + 1 < len(args):
            return args[i + 1]
    return None
