"""Frozen point-in-time capture of one namespace — the engine's single input.

The reference re-fetched cluster state ad hoc inside every agent and the
coordinator (reference: agents/mcp_coordinator.py:322-620 builds a fresh
``agent_context`` per runner; agents/resource_analyzer.py:44-70 fetches seven
collections again).  Here one :class:`ClusterSnapshot` is captured once per
analysis and shared by all agents, the feature extractor, and the topology
builder — one consistent view, one set of API round-trips.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class ClusterSnapshot:
    namespace: str
    captured_at: str
    pods: List[dict]
    deployments: List[dict]
    statefulsets: List[dict]
    daemonsets: List[dict]
    cronjobs: List[dict]
    services: List[dict]
    endpoints: List[dict]
    ingresses: List[dict]
    network_policies: List[dict]
    configmaps: List[dict]
    secrets: List[dict]
    pvcs: List[dict]
    resource_quotas: List[dict]
    hpas: List[dict]
    nodes: List[dict]
    node_metrics: Dict[str, Any]
    pod_metrics: Dict[str, Any]
    events: List[dict]
    # pod name -> {container -> log text} (tail-limited at capture time)
    logs: Dict[str, Dict[str, str]]
    traces: Dict[str, Any]
    # fetch failures swallowed during capture ([{"op", "error"}]): non-empty
    # means this snapshot is PARTIAL and every consumer should say so
    errors: List[Dict[str, str]] = dataclasses.field(default_factory=list)

    @classmethod
    def capture(
        cls,
        client,
        namespace: str,
        log_tail_lines: int = 200,
        max_log_pods: Optional[int] = None,
        include_traces: bool = True,
    ) -> "ClusterSnapshot":
        """Capture everything the analysis needs in one pass.

        ``max_log_pods=None`` fetches logs for every non-healthy pod plus a
        bounded sample of healthy ones — unlike the reference which sampled
        only the first 5 pods' logs (reference: mcp_coordinator.py:396-409)
        and could miss the faulty pod entirely.
        """
        from rca_tpu.cluster.sanitize import sanitize_objects

        # drain stale errors so this snapshot reports only ITS failures
        if hasattr(client, "collect_errors"):
            client.collect_errors()
        pods = sanitize_objects(client.get_pods(namespace))
        logs: Dict[str, Dict[str, str]] = {}
        pods_for_logs = _prioritize_pods_for_logs(pods, max_log_pods)
        for pod in pods_for_logs:
            pod_name = pod.get("metadata", {}).get("name", "")
            containers = pod.get("spec", {}).get("containers", []) or []
            per_container: Dict[str, str] = {}
            for c in containers:
                try:
                    per_container[c["name"]] = client.get_pod_logs(
                        namespace, pod_name, container=c["name"],
                        tail_lines=log_tail_lines,
                    )
                except Exception:
                    per_container[c["name"]] = ""
            logs[pod_name] = per_container

        traces: Dict[str, Any] = {}
        if include_traces:
            try:
                traces = {
                    "latency": client.get_service_latency_stats(namespace),
                    "error_rates": client.get_error_rate_by_service(namespace),
                    "dependencies": client.get_service_dependencies(namespace),
                    "slow_ops": client.find_slow_operations(namespace),
                }
            except Exception:
                traces = {}

        san = sanitize_objects
        return cls(
            namespace=namespace,
            captured_at=client.get_current_time(),
            pods=pods,
            deployments=san(client.get_deployments(namespace)),
            statefulsets=san(client.get_statefulsets(namespace)),
            daemonsets=san(client.get_daemonsets(namespace)),
            cronjobs=san(client.get_cronjobs(namespace)),
            services=san(client.get_services(namespace)),
            endpoints=san(client.get_endpoints(namespace)),
            ingresses=san(client.get_ingresses(namespace)),
            network_policies=san(client.get_network_policies(namespace)),
            configmaps=san(client.get_configmaps(namespace)),
            secrets=san(client.get_secrets(namespace)),
            pvcs=san(client.get_pvcs(namespace)),
            resource_quotas=san(client.get_resource_quotas(namespace)),
            hpas=san(client.get_hpas(namespace)),
            nodes=san(client.get_nodes()),
            node_metrics=client.get_node_metrics() or {},
            pod_metrics=client.get_pod_metrics(namespace) or {},
            events=san(client.get_events(namespace)),
            logs=logs,
            traces=traces,
            errors=(
                client.collect_errors()
                if hasattr(client, "collect_errors") else []
            ),
        )

    # convenience lookups -------------------------------------------------
    def pod_by_name(self, name: str) -> Optional[dict]:
        for p in self.pods:
            if p.get("metadata", {}).get("name") == name:
                return p
        return None

    def service_names(self) -> List[str]:
        return [s.get("metadata", {}).get("name", "") for s in self.services]


def _prioritize_pods_for_logs(pods: List[dict], max_pods: Optional[int]):
    """Unhealthy pods first; cap total fetches when max_pods is set."""

    def health_key(pod: dict) -> int:
        status = pod.get("status", {})
        if status.get("phase") not in ("Running", "Succeeded"):
            return 0
        for cs in status.get("containerStatuses", []) or []:
            if not cs.get("ready") or cs.get("restartCount", 0) > 0:
                return 0
        return 1

    unhealthy, healthy = [], []
    for p in pods:
        (healthy if health_key(p) else unhealthy).append(p)
    if max_pods is None:
        # all unhealthy pods + up to 25 healthy ones
        return unhealthy + healthy[:25]
    return (unhealthy + healthy)[:max_pods]
