"""Frozen point-in-time capture of one namespace — the engine's single input.

The reference re-fetched cluster state ad hoc inside every agent and the
coordinator (reference: agents/mcp_coordinator.py:322-620 builds a fresh
``agent_context`` per runner; agents/resource_analyzer.py:44-70 fetches seven
collections again).  Here one :class:`ClusterSnapshot` is captured once per
analysis and shared by all agents, the feature extractor, and the topology
builder — one consistent view, one set of API round-trips.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class ClusterSnapshot:
    """See module docstring.  ``columnar`` (ISSUE 10), when present, is a
    :class:`rca_tpu.cluster.columnar.ColumnarView` — the vectorized
    extractor inputs assembled at capture time from the client's columnar
    tables; the extractor uses it instead of the per-object dict scans
    (bit-identical by construction, property-tested).  Patched/derived
    snapshots must drop it (``dataclasses.replace(..., columnar=None)``)
    because a view describes exactly the capture that built it."""
    namespace: str
    captured_at: str
    pods: List[dict]
    deployments: List[dict]
    statefulsets: List[dict]
    daemonsets: List[dict]
    cronjobs: List[dict]
    services: List[dict]
    endpoints: List[dict]
    ingresses: List[dict]
    network_policies: List[dict]
    configmaps: List[dict]
    secrets: List[dict]
    pvcs: List[dict]
    resource_quotas: List[dict]
    hpas: List[dict]
    nodes: List[dict]
    node_metrics: Dict[str, Any]
    pod_metrics: Dict[str, Any]
    events: List[dict]
    # pod name -> {container -> log text} (tail-limited at capture time)
    logs: Dict[str, Dict[str, str]]
    traces: Dict[str, Any]
    # fetch failures swallowed during capture ([{"op", "error"}]): non-empty
    # means this snapshot is PARTIAL and every consumer should say so
    errors: List[Dict[str, str]] = dataclasses.field(default_factory=list)
    # columnar fast-path view (ISSUE 10); never part of the value
    columnar: Optional[Any] = dataclasses.field(
        default=None, compare=False, repr=False,
    )

    @classmethod
    def capture(
        cls,
        client,
        namespace: str,
        log_tail_lines: int = 200,
        max_log_pods: Optional[int] = None,
        include_traces: bool = True,
        columnar: bool = True,
        columnar_state: Optional[Any] = None,
        traces_from: Optional[Dict[str, Any]] = None,
    ) -> "ClusterSnapshot":
        """Capture everything the analysis needs in one pass.

        ``max_log_pods=None`` fetches logs for every non-healthy pod plus a
        bounded sample of healthy ones — unlike the reference which sampled
        only the first 5 pods' logs (reference: mcp_coordinator.py:396-409)
        and could miss the faulty pod entirely.

        When the client supports ``get_columnar`` (ISSUE 10) and
        ``RCA_COLUMNAR`` is on, the object lists, feature columns, and
        log-scan counts come from the incrementally-maintained columnar
        tables instead of per-object re-sanitize/re-scan sweeps —
        O(dirty rows) instead of O(objects) per capture.  ``columnar_state``
        (a :class:`rca_tpu.cluster.columnar.ColumnarClientState`) carries
        the mirror + cursor across repeated captures so only column DIFFS
        cross the client boundary (and the flight recording);
        ``traces_from`` reuses a previous capture's trace payloads when
        the caller knows traces were untouched (the busy-poll patch
        contract).  Both are ignored on the dict path.
        """
        from rca_tpu.cluster.sanitize import sanitize_objects
        from rca_tpu.config import columnar_enabled

        # callable check (not bare hasattr): a client subclass opts out of
        # the columnar surface with ``get_columnar = None`` — e.g. fault-
        # simulating test clients whose overridden getters must be hit
        if (
            columnar
            and columnar_enabled()
            and log_tail_lines == 200
            and callable(getattr(client, "get_columnar", None))
        ):
            snap = cls._capture_columnar(
                client, namespace,
                max_log_pods=max_log_pods,
                include_traces=include_traces,
                columnar_state=columnar_state,
                traces_from=traces_from,
            )
            if snap is not None:
                return snap

        # drain stale errors so this snapshot reports only ITS failures
        if hasattr(client, "collect_errors"):
            client.collect_errors()
        pods = sanitize_objects(client.get_pods(namespace))
        logs: Dict[str, Dict[str, str]] = {}
        pods_for_logs = _prioritize_pods_for_logs(pods, max_log_pods)
        for pod in pods_for_logs:
            pod_name = pod.get("metadata", {}).get("name", "")
            containers = pod.get("spec", {}).get("containers", []) or []
            per_container: Dict[str, str] = {}
            for c in containers:
                try:
                    per_container[c["name"]] = client.get_pod_logs(
                        namespace, pod_name, container=c["name"],
                        tail_lines=log_tail_lines,
                    )
                except Exception:
                    per_container[c["name"]] = ""
            logs[pod_name] = per_container

        traces: Dict[str, Any] = {}
        if include_traces:
            try:
                traces = {
                    "latency": client.get_service_latency_stats(namespace),
                    "error_rates": client.get_error_rate_by_service(namespace),
                    "dependencies": client.get_service_dependencies(namespace),
                    "slow_ops": client.find_slow_operations(namespace),
                }
            except Exception:
                traces = {}

        san = sanitize_objects
        return cls(
            namespace=namespace,
            captured_at=client.get_current_time(),
            pods=pods,
            deployments=san(client.get_deployments(namespace)),
            statefulsets=san(client.get_statefulsets(namespace)),
            daemonsets=san(client.get_daemonsets(namespace)),
            cronjobs=san(client.get_cronjobs(namespace)),
            services=san(client.get_services(namespace)),
            endpoints=san(client.get_endpoints(namespace)),
            ingresses=san(client.get_ingresses(namespace)),
            network_policies=san(client.get_network_policies(namespace)),
            configmaps=san(client.get_configmaps(namespace)),
            secrets=san(client.get_secrets(namespace)),
            pvcs=san(client.get_pvcs(namespace)),
            resource_quotas=san(client.get_resource_quotas(namespace)),
            hpas=san(client.get_hpas(namespace)),
            nodes=san(client.get_nodes()),
            node_metrics=client.get_node_metrics() or {},
            pod_metrics=client.get_pod_metrics(namespace) or {},
            events=san(client.get_events(namespace)),
            logs=logs,
            traces=traces,
            errors=(
                client.collect_errors()
                if hasattr(client, "collect_errors") else []
            ),
        )

    @classmethod
    def _capture_columnar(
        cls,
        client,
        namespace: str,
        max_log_pods: Optional[int],
        include_traces: bool,
        columnar_state: Optional[Any],
        traces_from: Optional[Dict[str, Any]],
    ) -> Optional["ClusterSnapshot"]:
        """Columnar capture (ISSUE 10): one ``get_columnar`` call (full
        tables once, column diffs after), log-text refetch only for pods
        whose rows changed, everything else assembled from the mirror.
        Returns None when the world is degenerate for columnar
        maintenance — the caller falls back to the dict sweep."""
        from rca_tpu.cluster.columnar import (
            ColumnarClientState,
            ColumnarUnsupported,
        )

        state = columnar_state or ColumnarClientState()
        if hasattr(client, "collect_errors"):
            client.collect_errors()  # drain stale errors
        payload = client.get_columnar(namespace, state.cursor)
        try:
            full, changed, _removed = state.apply(namespace, payload)
        except ColumnarUnsupported:
            return None
        tables = state.tables
        view = tables.build_view(max_log_pods=max_log_pods)

        # sampled log texts: fetch only what the mirror cannot vouch for
        # (everything on a full payload; changed/uncached pods on diffs)
        pods_tbl = tables.kinds["pods"]
        logs: Dict[str, Dict[str, str]] = {}
        for name in view.sampled_names:
            cached = state.log_texts.get(name)
            if cached is None or full or name in changed:
                row = pods_tbl.pos.get(name)
                pod = pods_tbl.objects[row] if row is not None else {}
                per_container: Dict[str, str] = {}
                for c in pod.get("spec", {}).get("containers", []) or []:
                    try:
                        per_container[c["name"]] = client.get_pod_logs(
                            namespace, name, container=c["name"],
                            tail_lines=200,
                        )
                    except Exception:
                        per_container[c["name"]] = ""
                state.log_texts[name] = per_container
                cached = per_container
            logs[name] = cached

        traces: Dict[str, Any] = {}
        if include_traces:
            if traces_from is not None:
                traces = traces_from
            else:
                try:
                    traces = {
                        "latency": client.get_service_latency_stats(
                            namespace),
                        "error_rates": client.get_error_rate_by_service(
                            namespace),
                        "dependencies": client.get_service_dependencies(
                            namespace),
                        "slow_ops": client.find_slow_operations(namespace),
                    }
                except Exception:
                    traces = {}

        k = tables.kinds
        return cls(
            namespace=namespace,
            captured_at=client.get_current_time(),
            pods=list(k["pods"].objects),
            deployments=list(k["deployments"].objects),
            statefulsets=list(k["statefulsets"].objects),
            daemonsets=list(k["daemonsets"].objects),
            cronjobs=list(k["cronjobs"].objects),
            services=list(k["services"].objects),
            endpoints=list(k["endpoints"].objects),
            ingresses=list(k["ingresses"].objects),
            network_policies=list(k["network_policies"].objects),
            configmaps=list(k["configmaps"].objects),
            secrets=list(k["secrets"].objects),
            pvcs=list(k["pvcs"].objects),
            resource_quotas=list(k["resource_quotas"].objects),
            hpas=list(k["hpas"].objects),
            nodes=list(tables.nodes),
            node_metrics=client.get_node_metrics() or {},
            pod_metrics={"pods": dict(tables.metric_recs)},
            events=list(tables.events),
            logs=logs,
            traces=traces,
            errors=(
                client.collect_errors()
                if hasattr(client, "collect_errors") else []
            ),
            columnar=view,
        )

    # convenience lookups -------------------------------------------------
    def pod_by_name(self, name: str) -> Optional[dict]:
        for p in self.pods:
            if p.get("metadata", {}).get("name") == name:
                return p
        return None

    def service_names(self) -> List[str]:
        return [s.get("metadata", {}).get("name", "") for s in self.services]


def _prioritize_pods_for_logs(pods: List[dict], max_pods: Optional[int]):
    """Unhealthy pods first; cap total fetches when max_pods is set."""

    def health_key(pod: dict) -> int:
        status = pod.get("status", {})
        if status.get("phase") not in ("Running", "Succeeded"):
            return 0
        for cs in status.get("containerStatuses", []) or []:
            if not cs.get("ready") or cs.get("restartCount", 0) > 0:
                return 0
        return 1

    unhealthy, healthy = [], []
    for p in pods:
        (healthy if health_key(p) else unhealthy).append(p)
    if max_pods is None:
        # all unhealthy pods + up to 25 healthy ones
        return unhealthy + healthy[:25]
    return (unhealthy + healthy)[:max_pods]
