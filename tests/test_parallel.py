"""Sharded propagation must equal the single-device engine bit-for-bit-ish."""

import numpy as np
import pytest

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.engine import GraphEngine
from rca_tpu.engine.propagate import default_params, propagate
from rca_tpu.parallel import make_mesh, shard_graph, sharded_propagate

import jax
import jax.numpy as jnp


def _reference_scores(features, src, dst, n_pad, params):
    f = np.zeros((n_pad, features.shape[1]), np.float32)
    f[: features.shape[0]] = features
    aw, hw = params.weight_arrays()
    return np.asarray(
        propagate(
            jnp.asarray(f), jnp.asarray(src), jnp.asarray(dst), aw, hw,
            params.steps, params.decay, params.explain_strength,
            params.impact_bonus, n_live=features.shape[0],
            error_contrast=params.error_contrast,
        )[4]
    )


@pytest.mark.parametrize("dp,sp", [(1, 8), (2, 4), (4, 2), (8, 1)])
def test_sharded_matches_dense(dp, sp):
    if len(jax.devices()) < dp * sp:
        pytest.skip("needs 8 devices")
    params = default_params()
    case = synthetic_cascade_arrays(100, n_roots=2, seed=11)
    graph = shard_graph(case.n, case.dep_src, case.dep_dst, sp)
    # hypothesis batch: the same features with per-hypothesis noise
    rng = np.random.default_rng(0)
    B = dp * 2
    batch = np.zeros((B, graph.n_pad, case.features.shape[1]), np.float32)
    for b in range(B):
        batch[b, : case.n] = np.clip(
            case.features + rng.uniform(0, 0.02, case.features.shape), 0, 1
        ).astype(np.float32)

    mesh = make_mesh([("dp", dp), ("sp", sp)])
    scores = np.asarray(sharded_propagate(mesh, batch, graph, params))
    assert scores.shape == (B, graph.n_pad)
    for b in range(B):
        ref = _reference_scores(
            batch[b, : case.n], case.dep_src, case.dep_dst, graph.n_pad, params
        )
        np.testing.assert_allclose(scores[b], ref, rtol=1e-5, atol=1e-6)
    # ranking still identifies the roots
    top2 = set(np.argsort(-scores[0])[:2].tolist())
    assert set(case.roots.tolist()) == top2


def test_shard_graph_partition():
    case = synthetic_cascade_arrays(64, n_roots=1, seed=0)
    g = shard_graph(case.n, case.dep_src, case.dep_dst, 4)
    assert g.n_pad % 4 == 0 and g.block == g.n_pad // 4
    # every real edge appears exactly once, in its source's shard
    real = int(g.mask.sum())
    assert real == len(case.dep_src)
    for k in range(4):
        m = g.mask[k] > 0
        assert ((g.src_global[k][m] // g.block) == k).all()
        assert (g.src_local[k][m] == g.src_global[k][m] - k * g.block).all()


@pytest.mark.parametrize("dp,sp", [(2, 4), (4, 2)])
def test_sharded_topk_matches_host_merge(dp, sp):
    """The on-device cross-shard top-k merge returns exactly the winners a
    host-side argsort of the full vector would."""
    if len(jax.devices()) < dp * sp:
        pytest.skip("needs 8 devices")
    from rca_tpu.parallel import sharded_topk

    params = default_params()
    case = synthetic_cascade_arrays(100, n_roots=2, seed=11)
    graph = shard_graph(case.n, case.dep_src, case.dep_dst, sp)
    rng = np.random.default_rng(1)
    B = dp * 2
    batch = np.zeros((B, graph.n_pad, case.features.shape[1]), np.float32)
    for b in range(B):
        batch[b, : case.n] = np.clip(
            case.features + rng.uniform(0, 0.02, case.features.shape), 0, 1
        ).astype(np.float32)
    mesh = make_mesh([("dp", dp), ("sp", sp)])
    scores = sharded_propagate(mesh, batch, graph, params)
    k = 5
    vals, idx = sharded_topk(mesh, scores, k)
    vals, idx = np.asarray(vals), np.asarray(idx)
    host = np.asarray(scores)
    for b in range(B):
        expect = np.argsort(-host[b])[:k]
        np.testing.assert_allclose(vals[b], host[b][expect], rtol=1e-6)
        # indices agree wherever values are not tied
        assert set(idx[b].tolist()) == set(expect.tolist())
    # the injected roots win in every hypothesis
    assert set(case.roots.tolist()) <= set(idx[0].tolist())


def test_multislice_mesh_and_propagate():
    """2 slices x (dp=2, sp=2) on the virtual 8-device CPU mesh: hypothesis
    batch sharded over (slice, dp) via DCN-style outer axis, nodes over sp."""
    import jax
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import GraphEngine
    from rca_tpu.engine.propagate import default_params
    from rca_tpu.parallel import shard_graph, sharded_propagate
    from rca_tpu.parallel.mesh import make_multislice_mesh

    devices = jax.devices()
    if len(devices) < 8:
        import pytest

        pytest.skip("needs 8 virtual devices")
    mesh = make_multislice_mesh(2, [("dp", 2), ("sp", 2)], devices[:8])
    assert mesh.axis_names == ("slice", "dp", "sp")

    case = synthetic_cascade_arrays(31, n_roots=1, seed=4)
    graph = shard_graph(case.n, case.dep_src, case.dep_dst, 2)
    B = 8
    rng = np.random.default_rng(0)
    batch = np.zeros((B, graph.n_pad, case.features.shape[1]), np.float32)
    for b in range(B):
        batch[b, : case.n] = np.clip(
            case.features + rng.uniform(0, 0.01, case.features.shape), 0, 1
        )
    scores = sharded_propagate(
        mesh, batch, graph, default_params(), batch_axes=("slice", "dp")
    )
    assert scores.shape == (B, graph.n_pad)
    res = GraphEngine().analyze_case(case, k=1)
    top = int(np.argmax(np.asarray(scores[0])[: case.n]))
    assert case.names[top] == res.ranked[0]["component"]

    # on-device top-k merge works on the multislice batch axis too
    from rca_tpu.parallel import sharded_topk

    vals, idx = sharded_topk(mesh, scores, 3, batch_axes=("slice", "dp"))
    assert np.asarray(idx).shape == (B, 3)
    assert int(np.asarray(idx)[0, 0]) == top


@pytest.mark.parametrize("segscan", ["0", "1"])
def test_sharded_engine_50k_scale(segscan, monkeypatch):
    """BASELINE.md row 5's config at full scale on the virtual mesh: the
    sharded engine must analyze the 50k-service multi-root cascade with
    exact score parity and identical ranking vs the dense engine (v5e-8
    hardware is unavailable in this environment; this pins the functional
    path at the real size, not just dryrun-tiny shapes).  segscan="1"
    forces the round-5 per-block segmented-scan layouts through BOTH
    engines (Pallas interpret mode off-TPU), proving the flagship 50k
    config is fast AND sharded — VERDICT r4 item 1."""
    from rca_tpu.engine.sharded_runner import ShardedGraphEngine

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    monkeypatch.setenv("RCA_SEGSCAN", segscan)
    case = synthetic_cascade_arrays(50_000, n_roots=5, seed=0)
    sh_eng = ShardedGraphEngine(spec="sp=8")
    if segscan == "1":
        from rca_tpu.parallel.sharded import sharded_seg_layouts_for

        graph = sh_eng._shard(case.n, case.dep_src, case.dep_dst)
        assert sharded_seg_layouts_for(graph) is not None, (
            "forced segscan must engage at the 50k tier"
        )
    sh = sh_eng.analyze_case(case, k=5)
    dense = GraphEngine().analyze_case(case, k=5)
    np.testing.assert_allclose(sh.score, dense.score, rtol=1e-5, atol=1e-6)
    assert [r["component"] for r in sh.ranked] == \
        [r["component"] for r in dense.ranked]
    roots = set(case.roots.tolist())
    assert roots <= set(np.argsort(-sh.score)[:5].tolist())


def test_sharded_segscan_matches_scatter_kernel(monkeypatch):
    """The per-block segmented-scan kernel is value-equivalent to the
    scatter kernel on the SAME sharded graph and hypothesis batch (the
    direct A/B the engagement gate switches between): allclose scores,
    identical top-3 per hypothesis.  fp32 sum order differs within a
    segment, hence allclose rather than byte equality."""
    from rca_tpu.config import RCAConfig, bucket_for
    from rca_tpu.parallel.sharded import sharded_seg_layouts_for

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    params = default_params()
    case = synthetic_cascade_arrays(900, n_roots=2, seed=7)
    buckets = RCAConfig().shape_buckets
    graph = shard_graph(
        case.n, case.dep_src, case.dep_dst, 4,
        n_pad_to=bucket_for(case.n + 1, buckets),
        e_pad_fn=lambda e: bucket_for(e, buckets),
    )
    assert graph.src_local.shape[1] % 128 == 0
    B = 4
    rng = np.random.default_rng(3)
    batch = np.zeros((B, graph.n_pad, case.features.shape[1]), np.float32)
    for b in range(B):
        batch[b, : case.n] = np.clip(
            case.features + rng.uniform(0, 0.05, case.features.shape), 0, 1
        )
    mesh = make_mesh([("dp", 2), ("sp", 4)])

    monkeypatch.setenv("RCA_SEGSCAN", "0")
    scatter = np.asarray(sharded_propagate(mesh, batch, graph, params))
    monkeypatch.setenv("RCA_SEGSCAN", "1")
    assert sharded_seg_layouts_for(graph) is not None
    seg = np.asarray(sharded_propagate(mesh, batch, graph, params))

    np.testing.assert_allclose(seg, scatter, rtol=1e-5, atol=1e-6)
    for b in range(B):
        assert np.argsort(-seg[b])[:3].tolist() == \
            np.argsort(-scatter[b])[:3].tolist()


def test_initialize_distributed_single_process_noop(monkeypatch):
    """Without a coordinator or TPU-pod env, the bootstrap must be a no-op
    that still reports the (single-process) topology, and calling it twice
    must be safe (idempotent by design, reference comparison: the reference
    had no distributed runtime at all, SURVEY.md §2.9)."""
    from rca_tpu.parallel import initialize_distributed

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "TPU_WORKER_HOSTNAMES",
                "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    info1 = initialize_distributed()
    info2 = initialize_distributed()
    assert info1["initialized"] is False
    assert info1["process_count"] == 1
    assert info1["process_index"] == 0
    # device counts are None before any JAX backend init (the strict no-op
    # must not initialize it), ints once some other code brought it up —
    # this test must pass in either order
    local, global_ = info1["local_device_count"], info1["global_device_count"]
    assert (local is None and global_ is None) or (local == global_ > 0)
    assert info2 == info1


@pytest.mark.parametrize(
    "n,spec,mode,fault_mix",
    [
        (50, "sp=4,dp=2", "standard", "crash"),
        (300, "auto", "standard", "crash"),
        (63, "sp=8", "standard", "crash"),
        (5, "sp=2,dp=1", "standard", "crash"),
        # hard cascade: adversarial + mixed archetypes exercises the
        # degree-normalized impact, background-median masking, and every
        # evidence channel through the sharded psum_scatter path
        (120, "sp=4,dp=2", "adversarial", "mixed"),
    ],
)
def test_sharded_engine_matches_dense_engine(n, spec, mode, fault_mix):
    """ShardedGraphEngine is the dense engine's drop-in twin: identical
    scores AND diagnostics (anomaly/upstream/impact) and identical ranked
    components on the same case — the property the analyze boundary relies
    on when make_engine auto-selects it."""
    from rca_tpu.engine import ShardedGraphEngine

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    case = synthetic_cascade_arrays(
        n, n_roots=min(2, max(1, n // 30)), seed=3,
        mode=mode, fault_mix=fault_mix,
    )
    dense = GraphEngine().analyze_case(case, k=5)
    sh = ShardedGraphEngine(spec=spec).analyze_case(case, k=5)
    np.testing.assert_allclose(sh.score, dense.score, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(sh.anomaly, dense.anomaly, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(sh.upstream, dense.upstream, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(sh.impact, dense.impact, rtol=1e-5, atol=1e-6)
    assert [r["component"] for r in sh.ranked] == \
        [r["component"] for r in dense.ranked]
    assert sh.engine.startswith("sharded(") and dense.engine == "single"


def test_make_engine_selection(monkeypatch):
    """RCA_SHARD drives the analyze-boundary engine choice at call time."""
    from rca_tpu.engine import GraphEngine as GE
    from rca_tpu.engine import ShardedGraphEngine, make_engine

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    monkeypatch.setenv("RCA_SHARD", "sp=4,dp=2")
    e = make_engine()
    assert isinstance(e, ShardedGraphEngine)
    assert (e.dp, e.sp) == (2, 4)
    monkeypatch.setenv("RCA_SHARD", "off")
    assert isinstance(make_engine(), GE)
    # unset: auto-shard because >1 device is visible
    monkeypatch.delenv("RCA_SHARD")
    assert isinstance(make_engine(), ShardedGraphEngine)
    # malformed spec fails loudly, not silently single-device
    monkeypatch.setenv("RCA_SHARD", "sp=banana")
    with pytest.raises(ValueError):
        make_engine()


def test_sharded_engine_shape_bucket_reuse():
    """Two graphs in the same shape bucket must produce the SAME padded
    shapes (the compile-cache contract the dense engine honors)."""
    from rca_tpu.engine import ShardedGraphEngine

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    eng = ShardedGraphEngine(spec="sp=4,dp=2")
    c1 = synthetic_cascade_arrays(40, 1, seed=0)
    c2 = synthetic_cascade_arrays(55, 1, seed=1)
    g1 = eng._shard(40, c1.dep_src, c1.dep_dst)
    g2 = eng._shard(55, c2.dep_src, c2.dep_dst)
    assert g1.n_pad == g2.n_pad
    assert g1.src_local.shape == g2.src_local.shape


def test_shard_spec_rejects_zero_and_misconfig_is_loud(monkeypatch):
    """sp=0/dp=0 fail at the parse site with a clear message, and a
    misconfigured RCA_SHARD raises out of the correlation path instead of
    silently demoting every analysis to the deterministic correlator."""
    from rca_tpu.agents import AnalysisContext
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.coordinator import correlate_findings
    from rca_tpu.engine.sharded_runner import parse_shard_spec

    for bad in ("sp=0", "dp=0,sp=4", "sp=-1"):
        with pytest.raises(ValueError, match="RCA_SHARD"):
            parse_shard_spec(bad, 8)

    monkeypatch.setenv("RCA_SHARD", f"sp={len(jax.devices()) * 64}")
    ctx = AnalysisContext(
        ClusterSnapshot.capture(
            MockClusterClient(five_service_world()), NS
        )
    )
    with pytest.raises(ValueError, match="devices"):
        correlate_findings({}, ctx=ctx, backend="jax")


# -- sharded streaming (VERDICT r3 item 3) ----------------------------------

@pytest.mark.parametrize("segscan", ["0", "1"])
def test_sharded_streaming_tick_parity_10k(segscan, monkeypatch):
    """Tick parity vs the dense streaming session at 10k: same set_all,
    same deltas, same quiet tick -> identical rankings and scores.  The
    sharded session keeps its feature buffer sp-sharded and merges top-k
    on device; parity means streaming and one-shot analyze cannot drift.
    segscan="1" forces the round-5 per-block segmented-scan tick kernel
    (layouts built once at session init)."""
    import numpy as np

    from rca_tpu.engine import ShardedGraphEngine
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.engine.streaming import StreamingSession
    from rca_tpu.parallel.streaming import ShardedStreamingSession

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    monkeypatch.setenv("RCA_SEGSCAN", segscan)
    c = synthetic_cascade_arrays(10_000, n_roots=3, seed=4)
    names = [f"s{i}" for i in range(c.n)]
    dense = StreamingSession(
        names, c.dep_src, c.dep_dst, c.features.shape[1],
        engine=GraphEngine(), k=5,
    )
    shard = ShardedStreamingSession(
        names, c.dep_src, c.dep_dst, c.features.shape[1],
        engine=ShardedGraphEngine(spec="sp=8"), k=5,
    )

    def ranking(out):
        return [(r["component"], round(r["score"], 5)) for r in out["ranked"]]

    dense.set_all(c.features)
    shard.set_all(c.features)
    assert ranking(dense.tick()) == ranking(shard.tick())

    rng = np.random.default_rng(0)
    delta = {
        int(i): np.clip(c.features[i] + rng.uniform(0, 0.5, c.features.shape[1]), 0, 1)
        for i in rng.integers(0, c.n, 7)
    }
    dense.update_many(delta)
    shard.update_many(delta)
    d, s = dense.tick(), shard.tick()
    assert ranking(d) == ranking(s)
    assert d["upload_rows"] == s["upload_rows"]
    # quiet tick: no pending rows -> no real upload, rankings stable
    dq, sq = dense.tick(), shard.tick()
    assert ranking(dq) == ranking(sq) == ranking(d)


def test_live_streaming_selects_sharded_session(monkeypatch):
    """The analyze-boundary selection reaches streaming: with RCA_SHARD
    set, a LiveStreamingSession builds the sharded session over the mesh
    and serves watch-driven polls from it."""
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine import LiveStreamingSession
    from rca_tpu.parallel.streaming import ShardedStreamingSession

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    monkeypatch.setenv("RCA_SHARD", "sp=8")
    world = five_service_world()
    live = LiveStreamingSession(MockClusterClient(world), NS, k=3)
    assert isinstance(live.session, ShardedStreamingSession)
    out = live.poll()
    assert out["quiet"] is True
    assert [r["component"] for r in out["ranked"]][:2] == [
        "database", "api-gateway",
    ]
    world.touch("pod", NS, world.pods[NS][0]["metadata"]["name"])
    out2 = live.poll()
    assert out2["quiet"] is False and out2["resynced"] is False
