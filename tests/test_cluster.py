"""Cluster-layer tests: protocol conformance (kills the reference's real/mock
interface skew, SURVEY.md §2.6), fixture content, generator ground truth."""

import numpy as np
import pytest

from rca_tpu.cluster import CLUSTER_CLIENT_METHODS, ClusterClient, MockClusterClient
from rca_tpu.cluster.fixtures import NS, five_service_world
from rca_tpu.cluster.generator import synthetic_cascade_arrays, synthetic_cascade_world
from rca_tpu.cluster.k8s_client import K8sApiClient, parse_cpu, parse_memory
from rca_tpu.cluster.snapshot import ClusterSnapshot


def test_protocol_has_full_surface():
    # the union surface incl. the methods that were mock-only in the reference
    for m in [
        "get_pods", "get_pod_logs", "get_events", "get_statefulsets",
        "get_endpoints", "get_service", "get_deployment", "get_resource_quotas",
        "get_trace_ids", "get_pvc", "get_hpas", "get_node_metrics",
    ]:
        assert m in CLUSTER_CLIENT_METHODS


@pytest.mark.parametrize("cls", [MockClusterClient, K8sApiClient])
def test_backends_conform_to_protocol(cls):
    for m in CLUSTER_CLIENT_METHODS:
        assert callable(getattr(cls, m, None)), f"{cls.__name__} missing {m}"


def test_mock_isinstance_protocol(five_svc_client):
    assert isinstance(five_svc_client, ClusterClient)


def test_five_service_fixture_faults(five_svc_client):
    c = five_svc_client
    pods = c.get_pods(NS)
    assert len(pods) == 6
    phases = {p["metadata"]["name"]: p["status"]["phase"] for p in pods}
    assert phases["api-gateway-6b7c8d9e5f-4q3zx"] == "Failed"
    db = c.get_pod(NS, "database-7c9f8b6d5e-3x5qp")
    cs = db["status"]["containerStatuses"][0]
    assert cs["state"]["waiting"]["reason"] == "CrashLoopBackOff"
    assert cs["restartCount"] == 5
    # broken services expose no endpoints
    eps = {e["metadata"]["name"]: e["subsets"] for e in c.get_endpoints(NS)}
    assert eps["database"] == [] and eps["api-gateway"] == []
    assert eps["frontend"]
    # events filtered by field selector
    warn = c.get_events(NS, field_selector="type!=Normal")
    assert all(e["type"] == "Warning" for e in warn)
    pod_events = c.get_events(
        NS,
        field_selector="involvedObject.kind=Pod,"
        "involvedObject.name=database-7c9f8b6d5e-3x5qp",
    )
    assert len(pod_events) == 1 and pod_events[0]["reason"] == "BackOff"
    # logs (namespace-first canonical arg order) + tail
    logs = c.get_pod_logs(NS, "database-7c9f8b6d5e-3x5qp", tail_lines=2)
    assert len(logs.splitlines()) == 2
    # metrics carry usage percentages computed against limits
    pm = c.get_pod_metrics(NS)["pods"]
    assert pm["backend-5b6d8f9c7d-2zf8g"]["cpu"]["usage_percentage"] == 95.0
    assert pm["resource-service-9d8e7f6c5b-1r5wq"]["memory"]["usage_percentage"] > 85


def test_snapshot_capture(five_svc_client):
    snap = ClusterSnapshot.capture(five_svc_client, NS)
    assert len(snap.pods) == 6
    assert len(snap.services) == 5
    assert snap.traces["error_rates"]["api-gateway"] == 0.25
    # logs captured for every pod (unhealthy prioritized)
    assert "database-7c9f8b6d5e-3x5qp" in snap.logs


def test_generator_arrays_ground_truth():
    from rca_tpu.features.schema import NUM_SERVICE_FEATURES

    case = synthetic_cascade_arrays(200, n_roots=3, seed=1)
    assert case.features.shape == (200, NUM_SERVICE_FEATURES)
    assert len(case.roots) == 3
    # roots carry a crash signal, non-roots essentially none
    crash = case.features[:, 0]
    root_mask = np.zeros(200, bool)
    root_mask[case.roots] = True
    assert crash[root_mask].min() > 0.8
    assert crash[~root_mask].max() < 0.2
    # DAG property: every dependency edge points to an earlier service
    assert (case.dep_dst < case.dep_src).all()


def test_cascade_modes_valid_and_distinct():
    """Every adversarial mode yields bounded features, recorded ground
    truth, and the property it advertises."""
    from rca_tpu.cluster.generator import CASCADE_MODES

    import pytest as _pytest

    with _pytest.raises(ValueError):
        synthetic_cascade_arrays(50, mode="bogus")

    for mode in CASCADE_MODES:
        case = synthetic_cascade_arrays(160, n_roots=2, seed=5, mode=mode)
        assert np.isfinite(case.features).all()
        assert case.features.min() >= 0.0 and case.features.max() <= 1.0
        assert len(case.roots) == 2
        assert (case.dep_dst < case.dep_src).all()

    # crashing_victims: some non-root services carry a crash signal
    cv = synthetic_cascade_arrays(160, n_roots=1, seed=5,
                                  mode="crashing_victims")
    root_mask = np.zeros(160, bool)
    root_mask[cv.roots] = True
    assert cv.features[~root_mask, 0].max() > 0.3
    # correlated_noise: background floor is clearly lifted vs standard
    cn = synthetic_cascade_arrays(160, n_roots=1, seed=5,
                                  mode="correlated_noise")
    std = synthetic_cascade_arrays(160, n_roots=1, seed=5)
    assert cn.features.mean() > std.features.mean() * 2
    # world carries the mode in ground truth
    w = synthetic_cascade_world(30, seed=3, mode="missing_signals")
    assert w.ground_truth["mode"] == "missing_signals"


def test_fault_archetypes():
    """Round-3 fault-mix: each archetype lights its own channel family
    (an image-pull root produces no logs — the container never started),
    "mixed" varies archetypes across roots, and the default "crash" path
    is byte-stable with pre-archetype seeds."""
    from rca_tpu.cluster.generator import ROOT_ARCHETYPES
    from rca_tpu.features.schema import SvcF

    channel_of = {
        "oom": SvcF.OOM, "image": SvcF.IMAGE,
        "config": SvcF.CONFIG, "pending": SvcF.PENDING,
    }
    for kind, chan in channel_of.items():
        case = synthetic_cascade_arrays(120, n_roots=2, seed=4,
                                        fault_mix=kind)
        assert case.root_kinds == [kind, kind]
        for r in case.roots.tolist():
            assert case.features[r, chan] >= 0.8
            assert case.features[r, SvcF.NOT_READY] >= 0.8
        # the never-started archetypes carry no log-error signal
        if kind in ("image", "pending"):
            for r in case.roots.tolist():
                assert case.features[r, SvcF.LOG_ERRORS] == 0.0

    # mixed: across seeds, more than one archetype appears
    kinds = {
        k
        for s in range(8)
        for k in synthetic_cascade_arrays(
            80, n_roots=2, seed=s, fault_mix="mixed"
        ).root_kinds
    }
    assert len(kinds) >= 3 and kinds <= set(ROOT_ARCHETYPES)

    # legacy byte-stability: the default path's features are unchanged by
    # the archetype machinery (same rng draw sequence)
    a = synthetic_cascade_arrays(100, n_roots=1, seed=11, mode="adversarial")
    b = synthetic_cascade_arrays(100, n_roots=1, seed=11, mode="adversarial",
                                 fault_mix="crash")
    np.testing.assert_array_equal(a.features, b.features)
    assert a.root_kinds == ["crash"]

    with pytest.raises(ValueError):
        synthetic_cascade_arrays(50, fault_mix="bogus")


def test_world_archetypes_drive_full_pipeline():
    """Dict-world archetypes exercise the WHOLE analyze path: the K8s
    states each archetype realizes (ImagePullBackOff waiting, OOMKilled
    termination, FailedScheduling, CreateContainerConfigError) must light
    the extractor's channels and rank top-1 through the coordinator."""
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.coordinator import RCACoordinator
    from rca_tpu.features.extract import extract_features
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.features.schema import SvcF

    channel_of = {
        "oom": SvcF.OOM, "image": SvcF.IMAGE,
        "config": SvcF.CONFIG, "pending": SvcF.PENDING,
    }
    for kind, chan in channel_of.items():
        w = synthetic_cascade_world(
            24, n_roots=1, seed=3, namespace="arch", fault_mix=kind,
        )
        root = w.ground_truth["fault_roots"][0]
        assert w.ground_truth["fault_kinds"] == [kind]
        client = MockClusterClient(w)
        snap = ClusterSnapshot.capture(client, "arch")
        fs = extract_features(snap)
        i = fs.service_names.index(root)
        # the extractor derives the archetype channel from K8s state, not
        # from the generator's arrays
        assert fs.service_features[i, chan] > 0.5, (
            kind, fs.service_features[i],
        )
        record = RCACoordinator(client).run_analysis("comprehensive", "arch")
        top = record["results"]["correlated"]["root_causes"][0]["component"]
        assert top == root, (kind, top, root)


def test_hard_modes_defeat_naive_but_not_engine():
    """The reason the modes exist: max-anomaly ranking fails where the
    explain-away engine does not (VERDICT round-1: accuracy numbers must
    not ride an easy generator)."""
    from rca_tpu.engine import GraphEngine

    engine = GraphEngine()
    eng_hits = naive_hits = 0
    trials = 8
    for seed in range(trials):
        c = synthetic_cascade_arrays(300, n_roots=1, seed=seed,
                                     mode="crashing_victims")
        root = int(c.roots[0])
        res = engine.analyze_case(c, k=1)
        eng_hits += int(np.argmax(res.score)) == root
        naive_hits += int(np.argmax(c.anomaly)) == root
    assert eng_hits == trials
    assert naive_hits <= trials // 2


def test_generator_world_consistency():
    w = synthetic_cascade_world(50, n_roots=1, seed=7)
    client = MockClusterClient(w)
    ns = w.ground_truth["namespace"]
    root = w.ground_truth["fault_roots"][0]
    pods = client.get_pods(ns)
    assert len(pods) == 50
    root_pod = client.get_pod(ns, f"{root}-0")
    state = root_pod["status"]["containerStatuses"][0]["state"]
    assert state["waiting"]["reason"] == "CrashLoopBackOff"
    # faulty service has no endpoints; an event was recorded for its pod
    eps = {e["metadata"]["name"]: e["subsets"] for e in client.get_endpoints(ns)}
    assert eps[root] == []
    reasons = {e["reason"] for e in client.get_events(ns)}
    assert "BackOff" in reasons


def test_degraded_client_yields_degraded_report():
    """VERDICT round-1 item 8: an RBAC-denied / failing fetch must surface
    as a PARTIAL-state analysis, not a clean bill of health."""
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.coordinator import RCACoordinator

    class RBACDeniedClient(MockClusterClient):
        """Events fetch is denied; failures land in the error channel."""

        # faults are simulated at the GETTER surface, so the columnar
        # fast path (which answers from the tables) must stay off
        get_columnar = None

        def __init__(self, world):
            super().__init__(world)
            self._errs = []

        def get_events(self, namespace, field_selector=None):
            self._errs.append({
                "op": "list_namespaced_event",
                "error": "ApiException: (403) Forbidden: events is forbidden",
            })
            return []

        def collect_errors(self, clear=True):
            out = list(self._errs)
            if clear:
                self._errs.clear()
            return out

    client = RBACDeniedClient(five_service_world())
    snap = ClusterSnapshot.capture(client, NS)
    assert snap.errors  # the denial is recorded on the snapshot
    assert any("Forbidden" in e["error"] for e in snap.errors)

    coord = RCACoordinator(client)
    rec = coord.run_analysis("comprehensive", NS)
    assert rec["status"] == "completed"
    degraded = rec["results"]["degraded"]
    assert any("Forbidden" in e["error"] for e in degraded["errors"])
    assert "PARTIAL cluster state" in rec["summary"]
    # chat turns carry the fetch errors in the exact-counts state too
    out = coord.process_user_query("how are my pods?", NS)
    assert out["cluster_state"]["fetch_errors"]

    # a healthy client stays clean: no degraded key, no note
    healthy = RCACoordinator(MockClusterClient(five_service_world()))
    rec2 = healthy.run_analysis("comprehensive", NS)
    assert "degraded" not in rec2["results"]
    assert "PARTIAL" not in rec2["summary"]


def test_deployment_resource_usage_join():
    """Deployment → pod metrics join tool (the reference declared it but
    only the mock could serve it; reference: mcp_metrics_agent.py:201-204)."""
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.llm import cluster_toolsets

    client = MockClusterClient(five_service_world())
    tools = {t.name: t for t in cluster_toolsets(client, NS)["metrics"]}
    spec = tools["get_deployment_resource_usage"]
    rows = spec.fn()
    assert rows
    by_name = {r["deployment"]: r for r in rows}
    assert "backend" in by_name
    b = by_name["backend"]
    assert b["pods_with_metrics"] >= 1
    assert b["cpu_usage_percentage_avg"] is not None
    assert b["per_pod"]
    # single-deployment filter
    only = spec.fn(deployment="backend")
    assert len(only) == 1 and only[0]["deployment"] == "backend"


def test_quantity_parsers():
    assert parse_cpu("100m") == 100.0
    assert parse_cpu("2") == 2000.0
    assert parse_cpu("1500000n") == 1.5
    assert parse_memory("128Mi") == 128 * 2**20
    assert parse_memory("1Gi") == 2**30
    assert parse_memory("1G") == 10**9
    assert parse_memory("500K") == 500_000.0


def test_created_ago_annotation(five_svc_client):
    """Resource details carry the reference's createdAgo humanization
    (reference: utils/k8s_client.py:949-1013) without mutating the stored
    world object."""
    from rca_tpu.findings import humanize_age

    assert humanize_age("2026-01-01T00:00:00Z", "2026-01-03T05:00:00Z") == "2d ago"
    assert humanize_age("2026-01-01T00:00:00Z", "2026-01-01T03:30:00Z") == "3h ago"
    assert humanize_age("2026-01-01T00:00:00Z", "2026-01-01T00:05:10Z") == "5m ago"
    assert humanize_age("garbage", "2026-01-01T00:00:00Z") == ""

    details = five_svc_client.get_resource_details(NS, "Deployment", "database")
    assert "createdAgo" in details
    stored = next(
        d for d in five_svc_client.world.deployments[NS]
        if d["metadata"]["name"] == "database"
    )
    assert "createdAgo" not in stored  # annotation never leaks into the world


def test_list_and_switch_contexts(tmp_path):
    """Context picker surface (reference: components/sidebar.py pickers):
    contexts listed across multi-file KUBECONFIG with the active one
    identified; switching to an unreachable context restores the previous
    one instead of stranding the client."""
    import os as _os

    import yaml

    a = tmp_path / "a.yaml"
    a.write_text(yaml.safe_dump({
        "current-context": "dev",
        "contexts": [{"name": "dev", "context": {"cluster": "c1"}}],
        "clusters": [], "users": [],
    }))
    b = tmp_path / "b.yaml"
    b.write_text(yaml.safe_dump({
        "contexts": [{"name": "prod", "context": {"cluster": "c2"}}],
        "clusters": [], "users": [],
    }))
    client = K8sApiClient(
        kubeconfig=f"{a}{_os.pathsep}{b}"
    )
    ctxs = client.list_contexts()
    assert ctxs["contexts"] == ["dev", "prod"]
    assert ctxs["current"] == "dev"
    # no live cluster here: the switch fails and restores the previous
    # context rather than stranding the client on a broken one
    assert client.switch_context("prod") is False
    assert client._context is None or client._context != "prod"
    # unparseable kubeconfig degrades to empty with the error recorded
    bad = tmp_path / "bad.yaml"
    bad.write_text("{unclosed")
    client2 = K8sApiClient(kubeconfig=str(bad))
    out = client2.list_contexts()
    assert out["contexts"] == []
    assert any(
        e["op"] == "list_contexts"
        for e in client2.collect_errors(clear=False)
    )
    # good + bad multi-file: the readable file's contexts survive AND the
    # bad file's failure is recorded — a partial view is never silent
    client3 = K8sApiClient(kubeconfig=f"{a}{_os.pathsep}{bad}")
    out3 = client3.list_contexts()
    assert out3["contexts"] == ["dev"]
    assert any(
        e["op"] == "list_contexts" and "bad.yaml" in e["error"]
        for e in client3.collect_errors(clear=False)
    )


def test_update_server_url_scoped_to_active_context(tmp_path):
    """Endpoint repair rewrites ONLY the current context's cluster (an
    unrelated prod cluster in the same file must keep its URL), leaves a
    .bak of the original, and fails loudly through the error channel when
    nothing matches (reference: components/sidebar.py:7-47)."""
    import yaml

    cfg = {
        "apiVersion": "v1",
        "current-context": "dev",
        "contexts": [
            {"name": "dev", "context": {"cluster": "dev-cluster"}},
            {"name": "prod", "context": {"cluster": "prod-cluster"}},
        ],
        "clusters": [
            {"name": "dev-cluster",
             "cluster": {"server": "https://old-tunnel:6443"}},
            {"name": "prod-cluster",
             "cluster": {"server": "https://prod:6443"}},
        ],
        "users": [],
    }
    path = tmp_path / "kubeconfig.yaml"
    path.write_text(yaml.safe_dump(cfg))

    client = K8sApiClient(kubeconfig=str(path))
    ok = client.update_server_url("https://tunnel.example:443")
    rewritten = yaml.safe_load(path.read_text())
    servers = {c["name"]: c["cluster"]["server"]
               for c in rewritten["clusters"]}
    assert servers["dev-cluster"] == "https://tunnel.example:443"
    assert servers["prod-cluster"] == "https://prod:6443"  # untouched
    backup = yaml.safe_load((tmp_path / "kubeconfig.yaml.bak").read_text())
    assert backup["clusters"][0]["cluster"]["server"] == "https://old-tunnel:6443"
    # reconnect result depends on the kubernetes lib being importable;
    # either way the scoped rewrite happened and no exception escaped
    assert ok in (True, False)

    # a kubeconfig with no matching cluster fails loudly
    empty = tmp_path / "empty.yaml"
    empty.write_text(yaml.safe_dump({"clusters": []}))
    client2 = K8sApiClient(kubeconfig=str(empty))
    assert client2.update_server_url("https://x") is False
    errs = client2.collect_errors(clear=False)
    assert any(e["op"] == "update_server_url" for e in errs)


def test_update_server_url_multi_file_kubeconfig(tmp_path):
    """The colon-separated KUBECONFIG form repairs the file that actually
    defines the active context's cluster."""
    import os

    import yaml

    first = tmp_path / "first.yaml"
    first.write_text(yaml.safe_dump({
        "clusters": [{"name": "other",
                      "cluster": {"server": "https://other:6443"}}],
        "contexts": [{"name": "o", "context": {"cluster": "other"}}],
    }))
    second = tmp_path / "second.yaml"
    second.write_text(yaml.safe_dump({
        "current-context": "dev",
        "contexts": [{"name": "dev", "context": {"cluster": "dev-cluster"}}],
        "clusters": [{"name": "dev-cluster",
                      "cluster": {"server": "https://old:6443"}}],
    }))
    client = K8sApiClient(
        kubeconfig=os.pathsep.join([str(first), str(second)])
    )
    client.update_server_url("https://new:443")
    assert "https://other:6443" in first.read_text()  # untouched
    assert "https://new:443" in second.read_text()


def test_reload_config_reports_connection_state(tmp_path):
    # a missing kubeconfig can never yield a live API connection
    client = K8sApiClient(kubeconfig=str(tmp_path / "missing.yaml"))
    assert client.reload_config() is False
    # disconnected client stays usable: getters degrade to empty
    assert client.get_pods("default") == []


def test_update_server_url_retry_preserves_first_backup(tmp_path):
    """A second repair (e.g. after a typo'd URL) must not clobber the
    pristine backup with the mangled intermediate."""
    import yaml

    path = tmp_path / "kc.yaml"
    path.write_text(yaml.safe_dump({
        "current-context": "dev",
        "contexts": [{"name": "dev", "context": {"cluster": "c1"}}],
        "clusters": [{"name": "c1",
                      "cluster": {"server": "https://original:6443"}}],
    }))
    client = K8sApiClient(kubeconfig=str(path))
    client.update_server_url("https://typo:443")
    client.update_server_url("https://corrected:443")
    backup = yaml.safe_load((tmp_path / "kc.yaml.bak").read_text())
    assert backup["clusters"][0]["cluster"]["server"] == "https://original:6443"
    assert "https://corrected:443" in path.read_text()
