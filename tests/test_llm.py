"""LLM backend: tool loop actually executes tools; offline determinism;
JSON rescue parsing; provider resolution; LLM agents degrade to rules."""

import json

import pytest

from rca_tpu.agents import AnalysisContext
from rca_tpu.agents.llm_agent import LLMAgent, make_llm_agents
from rca_tpu.cluster.fixtures import NS, five_service_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.llm import (
    LLMClient,
    OfflineProvider,
    ProviderReply,
    ToolCall,
    cluster_toolsets,
    make_provider,
    parse_json_response,
)


@pytest.fixture(scope="module")
def client():
    return MockClusterClient(five_service_world())


@pytest.fixture(scope="module")
def ctx(client):
    return AnalysisContext(ClusterSnapshot.capture(client, NS))


def test_offline_provider_resolution(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    monkeypatch.delenv("ANTHROPIC_API_KEY", raising=False)
    monkeypatch.delenv("RCA_LLM_PROVIDER", raising=False)
    assert make_provider().name == "offline"
    monkeypatch.setenv("RCA_LLM_PROVIDER", "offline")
    assert make_provider().name == "offline"


def test_tool_loop_executes_real_tools(client):
    """The loop must run the declared tools against the cluster client and
    feed their output back — the reference never did this."""
    llm = LLMClient(provider=OfflineProvider())
    tools = cluster_toolsets(client, NS)["traces"]
    out = llm.analyze("analyze traces", tools=tools)
    executed = {s["tool"] for s in out["reasoning_steps"] if "tool" in s}
    assert "get_service_latency_stats" in executed
    assert "get_error_rate_by_service" in executed
    # tool output flowed into the final answer (offline echoes evidence)
    assert "api-gateway" in out["final_analysis"]


def test_tool_execution_rejects_unknown_args(client):
    tools = cluster_toolsets(client, NS)["logs"]
    get_logs = next(t for t in tools if t.name == "get_pod_logs")
    # unknown argument keys are dropped, not passed through
    text = get_logs.execute(
        {"pod_name": "database-7c9f8b6d5e-3x5qp", "bogus": 1}
    )
    assert "Database initialization failed" in text


def test_tool_execution_returns_error_payload(client):
    tools = cluster_toolsets(client, NS)["traces"]
    details = next(t for t in tools if t.name == "get_trace_details")
    out = json.loads(details.execute({"trace_id": "no-such-trace"}))
    assert "error" in out or out == {}


def test_parse_json_rescue_paths():
    assert parse_json_response('{"a": 1}') == {"a": 1}
    assert parse_json_response('text\n```json\n{"a": 1}\n```\nmore') == {"a": 1}
    assert parse_json_response('prefix {"a": {"b": 2}} suffix') == {"a": {"b": 2}}
    assert parse_json_response("no json here") is None
    assert parse_json_response("") is None


def test_prompt_log_hook_records_interactions():
    records = []
    llm = LLMClient(provider=OfflineProvider(), log_fn=records.append)
    llm.generate_completion("hello")
    llm.generate_structured_output("give json")
    assert len(records) == 2
    assert records[0]["additional_context"]["provider"] == "offline"
    assert records[1]["additional_context"]["kind"] == "structured"


def test_llm_agents_degrade_to_deterministic_rules(client, ctx):
    """Offline provider yields no structured findings -> every LLM agent
    falls back to its rule twin and still produces findings."""
    llm = LLMClient(provider=OfflineProvider())
    agents = make_llm_agents(llm, cluster_client=client, namespace=NS)
    assert set(agents) == {
        "resources", "metrics", "logs", "events", "topology", "traces",
    }
    res = agents["resources"].analyze(ctx)
    assert res.findings  # deterministic fallback fired
    assert any("database" in f["component"] for f in res.findings)


def test_llm_agent_parses_structured_findings(ctx):
    """A provider that returns findings JSON populates findings directly."""

    class ScriptedProvider(OfflineProvider):
        def complete(self, messages, tools=None, temperature=0.2,
                     max_tokens=2000, json_mode=False):
            if json_mode:
                return ProviderReply(text=json.dumps({
                    "findings": [{
                        "component": "Pod/database-7c9f8b6d5e-3x5qp",
                        "issue": "crash looping",
                        "severity": "critical",
                        "evidence": "restart count 5",
                        "recommendation": "fix the init script",
                    }],
                    "summary": "database down",
                }))
            return ProviderReply(text="the database pod is crash looping")

    agent = LLMAgent("logs", LLMClient(provider=ScriptedProvider()))
    res = agent.analyze(ctx)
    assert len(res.findings) == 1
    f = res.findings[0]
    assert f["severity"] == "critical"
    assert f["source"] == "llm"
    assert res.summary == "database down"


def test_coordinator_llm_agents_bind_namespace_and_cache():
    """Coordinator-level LLM path: agents are built once (cached), tools are
    bound to the SNAPSHOT's namespace at analyze time (regression: they were
    bound to namespace "" at construction), and structured findings flow
    through without the deterministic fallback firing."""
    from rca_tpu.coordinator import RCACoordinator

    calls = []

    class SpyClient(MockClusterClient):
        def get_pods(self, namespace):
            calls.append(namespace)
            return super().get_pods(namespace)

    class ScriptedProvider(OfflineProvider):
        def complete(self, messages, tools=None, temperature=0.2,
                     max_tokens=2000, json_mode=False):
            if json_mode:
                return ProviderReply(text=json.dumps({
                    "findings": [{
                        "component": "Pod/database-7c9f8b6d5e-3x5qp",
                        "issue": "crash looping",
                        "severity": "critical",
                        "evidence": "restart count 5",
                        "recommendation": "fix the init script",
                    }],
                    "summary": "database down",
                }))
            return super().complete(
                messages, tools=tools, temperature=temperature,
                max_tokens=max_tokens, json_mode=json_mode,
            )

    coord = RCACoordinator(
        SpyClient(five_service_world()),
        llm_client=LLMClient(provider=ScriptedProvider()),
        use_llm_agents=True,
    )
    assert coord._agent_for("logs") is coord._agent_for("logs")  # cached

    rec = coord.run_analysis("logs", NS)
    assert rec["status"] == "completed"
    res = rec["results"]["logs"]
    # the tool loop really executed the logs toolset's get_pods
    assert any(s.get("tool") == "get_pods" for s in res["reasoning_steps"])
    # every cluster call (snapshot capture AND tools) hit the real namespace
    assert NS in calls
    assert "" not in calls
    # structured findings were adopted from the provider, not the fallback
    assert res["findings"][0]["source"] == "llm"
    assert res["summary"] == "database down"


def test_quota_error_classification():
    from rca_tpu.llm.providers import LLMQuotaExceeded, _classify_error

    assert isinstance(
        _classify_error(Exception("Rate limit exceeded")), LLMQuotaExceeded
    )
    assert not isinstance(
        _classify_error(Exception("boom")), LLMQuotaExceeded
    )


def test_runtime_quota_failover_lands_on_offline(monkeypatch):
    """A provider that 429s mid-session fails over (reference: app.py:50-67)
    and, with no API keys available, lands on the offline provider."""
    from rca_tpu.llm.providers import LLMQuotaExceeded, OfflineProvider

    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    monkeypatch.delenv("ANTHROPIC_API_KEY", raising=False)

    class QuotaProvider(OfflineProvider):
        name = "openai"

        def complete(self, *a, **k):
            raise LLMQuotaExceeded("429 rate limit")

    events = []
    llm = LLMClient(provider=QuotaProvider(), log_fn=events.append)
    out = llm.generate_completion("hello")
    assert out  # offline provider answered
    assert llm.provider.name == "offline"
    assert any(
        e["additional_context"].get("kind") == "provider_failover"
        for e in events
    )
