"""Native C++ scanner: build, parity with the Python regex oracle, speed."""

import time

import numpy as np
import pytest

from rca_tpu.features.logscan import (
    LOG_PATTERN_NAMES,
    scan_text,
    scan_text_python,
)
from rca_tpu.native import (
    SPEC_CLASS_NAMES,
    native_available,
    scan_text_native,
)

SAMPLES = [
    "",
    "INFO: all good\n" * 50,
    "ERROR: Database initialization failed\nFATAL: could not open file\n",
    "container oomkilled by kernel: out of memory\nsignal: killed\n",
    "oom-kill event; oom_killer invoked; OOMKilled\n",
    "connection refused to db:5432 (ECONNREFUSED)\n",
    "request timed out; deadline exceeded; ETIMEDOUT; timeout after 5s\n",
    "time out while waiting; timed-out again; time-out\n",
    "Back-off restarting failed container\nCrashLoopBackOff seen\n",
    "backoff restarting container now\n",
    "api server error; StatusCode=503 returned; StatusCode=5xx\n",
    "API SERVER ERROR uppercase should not match api_error\n",
    "Unable to attach or mount volumes: timed out\n",
    "MountVolume.SetUp failed for volume xyz\n",
    "ErrImagePull: failed to pull image 'x:1'\nImagePullBackOff\n",
    "could not resolve host; no such host; DNS resolution failed\n",
    "401 Unauthorized; authentication failure for user\n",
    "invalid configuration detected\nconfigmap \"app-cfg\" not found\n",
    "secret my-secret key not found in namespace\n",
    "HTTP 500 Internal Server Error\ninternal server error again\n",
    "Exception in thread main\nTraceback (most recent call last)\n",
    "errors everywhere but the word error stands alone: error!\n",
    "forbidden access; this_is_forbidden_token should not wordmatch\n",
    "panic: runtime error\npanicking is fine\n",
    "CRITICAL failure; criticality is not critical-word? critical.\n",
    "fatal: FATAL mistake; fatally wrong\n",
    # mixed real-world-ish blob
    (
        "2026-01-01T00:00:00Z ERROR failed to pull image registry/app:9\n"
        "2026-01-01T00:00:01Z warn connection refused: backend:8080\n"
        "2026-01-01T00:00:02Z info retrying in 5s\n"
        "2026-01-01T00:00:03Z ERROR Exception: deadline exceeded\n"
    ) * 20,
]


def test_spec_covers_all_pattern_classes():
    assert SPEC_CLASS_NAMES == LOG_PATTERN_NAMES


@pytest.mark.skipif(not native_available(), reason="no C++ toolchain")
@pytest.mark.parametrize("idx", range(len(SAMPLES)))
def test_native_matches_python_regex(idx):
    text = SAMPLES[idx]
    got = scan_text_native(text)
    want = scan_text_python(text)
    assert got is not None
    mismatches = {
        LOG_PATTERN_NAMES[i]: (int(got[i]), int(want[i]))
        for i in range(len(want))
        if got[i] != want[i]
    }
    assert not mismatches, f"native != python on sample {idx}: {mismatches}"


@pytest.mark.skipif(not native_available(), reason="no C++ toolchain")
def test_native_matches_on_fixture_logs():
    from rca_tpu.cluster.fixtures import five_service_world

    world = five_service_world()
    for ns_logs in world.logs.values():
        for per_container in ns_logs.values():
            for text in (
                per_container.values()
                if isinstance(per_container, dict) else [per_container]
            ):
                got = scan_text_native(text)
                want = scan_text_python(text)
                assert (got == want).all()


def test_scan_text_dispatches_and_agrees():
    text = SAMPLES[-1]
    assert (scan_text(text) == scan_text_python(text)).all()


@pytest.mark.skipif(not native_available(), reason="no C++ toolchain")
def test_native_is_faster_on_bulk_logs():
    text = SAMPLES[-1] * 50  # ~80 log lines * 50
    # warm both
    scan_text_native(text), scan_text_python(text)
    t0 = time.perf_counter()
    for _ in range(5):
        scan_text_native(text)
    native_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        scan_text_python(text)
    python_s = time.perf_counter() - t0
    # conservative bound to avoid flakiness; typical speedup is ~5-15x
    assert native_s < python_s, (native_s, python_s)


def test_native_sanitize_exact_parity_with_python():
    """The C sanitizer must produce DEEP-EQUAL output to the Python spec on
    fuzzed K8s objects (the Python implementation is the contract; any
    divergence is a bug in sanitizec.c)."""
    import copy
    import random

    import pytest

    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.sanitize import sanitize_object
    from rca_tpu.native import load_sanitize

    native = load_sanitize()
    if native is None:
        pytest.skip("no toolchain / native sanitize disabled")

    def mangle(obj, rng):
        if isinstance(obj, dict):
            for k in list(obj):
                r = rng.random()
                if r < 0.1:
                    del obj[k]
                elif r < 0.18:
                    obj[k] = None
                elif r < 0.2:
                    obj[k] = 123  # wrong scalar type
                else:
                    mangle(obj[k], rng)
        elif isinstance(obj, list):
            for i, item in enumerate(obj):
                if rng.random() < 0.06:
                    obj[i] = None
                else:
                    mangle(item, rng)

    world = five_service_world()
    objects = (
        world.pods[NS] + world.services[NS] + world.deployments[NS]
        + world.events[NS] + world.endpoints[NS] + world.hpas[NS]
        + world.ingresses[NS] + world.network_policies[NS]
    )
    checked = 0
    for seed in range(30):
        rng = random.Random(seed)
        for obj in copy.deepcopy(objects):
            mangle(obj, rng)
            py = sanitize_object(copy.deepcopy(obj))
            c = native.sanitize_object(copy.deepcopy(obj))
            assert c == py, f"seed {seed}: divergence on {obj!r:.300}"
            checked += 1
    assert checked > 500

    # wrong-typed metadata must repair to {name,labels} in BOTH
    # implementations (the fuzz above hits this probabilistically; this
    # pins it deterministically)
    for bad in ("x", 123, ["y"]):
        obj = {"template": {"metadata": bad}}
        py = sanitize_object(copy.deepcopy(obj))
        c = native.sanitize_object(copy.deepcopy(obj))
        assert py["template"]["metadata"] == {"name": "", "labels": {}}
        assert c == py

    # copy-on-write parity: a well-formed object passes through unchanged
    good = {
        "metadata": {"name": "x", "labels": {"app": "x"}},
        "spec": {"containers": [{"name": "c", "env": [
            {"name": "A", "value": "1"},
        ]}]},
    }
    assert native.sanitize_object(good) is good
