"""Watch surface tests: journal feed semantics, quiet-poll fast path,
scoped snapshot patching, expiry → resync, and the live watch pumps
(driven by a stub ``kubernetes`` module, no cluster)."""

from __future__ import annotations

import sys
import time
import types

import numpy as np
import pytest

from rca_tpu.cluster.fixtures import NS, five_service_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.engine import LiveStreamingSession


# -- journal feed semantics --------------------------------------------------

def test_mock_watch_cursor_and_dedup():
    world = five_service_world()
    client = MockClusterClient(world)
    head = client.watch_changes(NS, None)
    assert head["supported"] and not head["expired"]

    base_seq = world.journal_seq
    world.touch("pod", NS, "p1")
    world.touch("pod", NS, "p1")        # dedups
    world.touch("pod", "other-ns", "x")  # other namespace filters out
    world.touch("event", NS, "p1")       # distinct kind survives dedup
    out = client.watch_changes(NS, head["cursor"])
    assert [
        {"kind": c["kind"], "name": c["name"]} for c in out["changes"]
    ] == [
        {"kind": "pod", "name": "p1"},
        {"kind": "event", "name": "p1"},
    ]
    # each change carries the touched object's resourceVersion (ISSUE 10
    # row-write key); dedupe keeps the NEWEST one — p1 was touched at
    # base+1 then base+2, so its deduped record reports base+2
    assert out["changes"][0]["rv"] == str(base_seq + 2)
    assert out["changes"][1]["rv"] == str(base_seq + 4)
    # the returned cursor has consumed everything
    again = client.watch_changes(NS, out["cursor"])
    assert again["changes"] == [] and not again["expired"]


def test_mock_watch_expires_past_trim():
    world = five_service_world()
    world.journal_cap = 10
    client = MockClusterClient(world)
    head = client.watch_changes(NS, None)
    for i in range(50):  # overflow the cap: old entries trim away
        world.touch("pod", NS, f"p{i}")
    out = client.watch_changes(NS, head["cursor"])
    assert out["expired"] is True
    # recovery: reopen at head, consume normally
    head2 = client.watch_changes(NS, None)
    world.touch("pod", NS, "fresh")
    assert [
        {"kind": c["kind"], "name": c["name"]}
        for c in client.watch_changes(NS, head2["cursor"])["changes"]
    ] == [
        {"kind": "pod", "name": "fresh"}
    ]


# -- quiet-poll fast path ----------------------------------------------------

class SpyClient(MockClusterClient):
    """Counts the expensive calls so tests can prove what a poll did."""

    def __init__(self, world):
        super().__init__(world)
        self.calls = {"get_pods": 0, "get_pod": 0, "get_events": 0}

    def get_pods(self, namespace):
        self.calls["get_pods"] += 1
        return super().get_pods(namespace)

    def get_pod(self, namespace, name):
        self.calls["get_pod"] += 1
        return super().get_pod(namespace, name)

    def get_events(self, namespace, field_selector=None):
        self.calls["get_events"] += 1
        return super().get_events(namespace, field_selector)


def test_quiet_poll_never_sweeps():
    """A poll with no changes must not list the namespace or re-extract —
    that is the entire point of the watch path (VERDICT r2 item 6)."""
    world = five_service_world()
    client = SpyClient(world)
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=100)
    client.calls = {k: 0 for k in client.calls}

    out = live.poll()
    assert out["quiet"] is True
    assert out["changed_rows"] == 0
    assert client.calls["get_pods"] == 0
    assert client.calls["get_events"] == 0
    # and it's fast on the host: no capture, no extraction
    assert out["capture_ms"] < 50


def test_busy_poll_fetches_only_changed_objects():
    from rca_tpu.cluster.world import waiting_status

    world = five_service_world()
    client = SpyClient(world)
    # this test pins the DICT patch path's call scoping (the live-cluster
    # shape — no columnar surface there); columnar busy polls are covered
    # in tests/test_columnar.py
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=100,
                                use_columnar=False)
    client.calls = {k: 0 for k in client.calls}

    pod = world.pods[NS][0]
    name = pod["metadata"]["name"]
    app = pod["metadata"]["labels"].get("app", "frontend")
    pod["status"]["phase"] = "Running"
    pod["status"]["containerStatuses"] = [
        waiting_status(app, "CrashLoopBackOff", restarts=7, last_exit_code=1)
    ]
    world.touch("pod", NS, name)

    out = live.poll()
    assert out["quiet"] is False and out["resynced"] is False
    assert out["changed_rows"] >= 1
    # scoped: ONE pod re-read, no namespace pod list
    assert client.calls["get_pods"] == 0
    assert client.calls["get_pod"] == 1


def test_expired_feed_recovers_incrementally():
    """Feed expiry triggers the GRACEFUL recovery (VERDICT r3 item 6):
    one pod re-list + value diff, no full capture, no session rebuild —
    recovery cost scales with drift, not graph size."""
    from rca_tpu.cluster.world import waiting_status

    world = five_service_world()
    world.journal_cap = 5
    client = SpyClient(world)
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=100)
    assert live.resyncs == 0
    # real drift while the feed is blind: one pod goes crashloop (mutate
    # by REPLACEMENT — the session's retained snapshot aliases the world's
    # dicts, so an in-place edit would hide the drift from the value diff)
    import copy

    pod = copy.deepcopy(world.pods[NS][0])
    app = pod["metadata"]["labels"].get("app", "frontend")
    pod["status"]["containerStatuses"] = [
        waiting_status(app, "CrashLoopBackOff", restarts=9, last_exit_code=1)
    ]
    world.pods[NS][0] = pod
    for i in range(20):
        world.touch("pod", NS, f"ghost-{i}")  # trim past the cursor
    client.calls = {k: 0 for k in client.calls}
    out = live.poll()
    assert out.get("recovered") is True
    assert out["resynced"] is False          # no session rebuild
    assert live.resyncs == 0
    assert out["drift_pods"] == 1            # exactly the mutated pod
    assert out["changed_rows"] >= 1          # its features re-uploaded
    # scoped: ONE namespace pod list, no per-pod refetch loop
    assert client.calls["get_pods"] == 1
    assert client.calls["get_pod"] == 0
    # recovery pulls the full topology check forward to the NEXT poll
    # (lost notifications could have been topology kinds the cheap path
    # cannot verify) — one sweep, then quiet incremental polls resume
    out2 = live.poll()
    assert out2["quiet"] is False and out2["resynced"] is False
    out3 = live.poll()
    assert out3["quiet"] is True


def test_topology_drift_during_expiry_caught_next_poll():
    """A service added while the feed was expired: the cheap recovery
    cannot see it, but the forced next-poll topology check rebuilds the
    session — the stale-edge window is bounded at ONE tick regardless of
    topology_check_every."""
    from rca_tpu.cluster.world import make_deployment, make_service

    world = five_service_world()
    world.journal_cap = 5
    client = SpyClient(world)
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=10_000)
    n0 = len(live._names)
    world.add("services", NS, make_service("late-arrival", NS))
    world.add("deployments", NS, make_deployment("late-arrival", NS, "late-arrival"))
    for i in range(20):
        world.touch("pod", NS, f"ghost-{i}")  # trim past the cursor
    out = live.poll()
    assert out.get("recovered") is True      # cheap recovery ran...
    assert len(live._names) == n0            # ...and cannot see the service
    out2 = live.poll()                       # forced topology check
    assert out2["resynced"] is True
    assert len(live._names) == n0 + 1


def test_topology_kind_change_forces_resync():
    from rca_tpu.cluster.world import make_deployment, make_service

    world = five_service_world()
    client = SpyClient(world)
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=100)
    n0 = len(live._names)
    world.add("services", NS, make_service("brandnew", NS))
    world.add("deployments", NS, make_deployment("brandnew", NS, "brandnew"))
    out = live.poll()
    assert out["resynced"] is True
    assert len(live._names) == n0 + 1


def test_traces_change_kind_updates_features_and_edges():
    """A journaled trace update patches the error-rate/latency channels
    without a sweep — and resyncs when the trace DEPENDENCIES (which shape
    the device-pinned edges) changed."""
    world = five_service_world()
    client = SpyClient(world)
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=100)
    client.calls = {k: 0 for k in client.calls}

    # feature-only trace change: frontend's error rate spikes
    world.traces["error_rates"][NS]["frontend"] = 0.95
    world.touch("traces", NS, "frontend")
    out = live.poll()
    assert out["quiet"] is False and out["resynced"] is False
    assert out["changed_rows"] >= 1
    assert client.calls["get_pods"] == 0  # no sweep

    # dependency-shape trace change: new edge appears -> resync
    world.traces["dependencies"][NS]["frontend"] = list(
        world.traces["dependencies"][NS].get("frontend", [])
    ) + ["resource-service"]
    world.touch("traces", NS, "frontend")
    out2 = live.poll()
    assert out2["resynced"] is True


def test_cursor_at_trim_boundary_not_expired():
    """Off-by-one regression: a cursor at journal_floor - 1 still has
    every needed entry retained and must NOT read as expired."""
    world = five_service_world()
    world.journal_cap = 5
    client = MockClusterClient(world)
    # place the cursor exactly at what will become floor - 1
    base = world.journal_seq
    for i in range(5):
        world.touch("pod", NS, f"p{i}")
    # journal now holds seqs base+1..base+5; trim begins beyond the cap
    world.touch("pod", NS, "p5")  # trims to base+2..base+6, floor=base+2
    out = client.watch_changes(NS, str(base + 1))
    assert out["expired"] is False
    assert [c["name"] for c in out["changes"]] == [
        "p2", "p3", "p4", "p5",
    ] or len(out["changes"]) == 5


def test_use_watch_false_forces_sweep_strategy():
    world = five_service_world()
    client = SpyClient(world)
    live = LiveStreamingSession(
        client, NS, k=3, use_watch=False, topology_check_every=100,
        use_columnar=False,  # pin the dict sweep's call shape
    )
    client.calls = {k: 0 for k in client.calls}
    out = live.poll()
    assert "quiet" in out and out["quiet"] is False
    assert client.calls["get_pods"] == 1  # full sweep ran


def test_columnar_sweep_never_lists_the_namespace():
    """The columnar twin of the sweep-strategy test (ISSUE 10): with the
    columnar feed active, even a FULL sweep costs zero object-list calls
    — the tables answer, the journal keeps them fresh."""
    world = five_service_world()
    client = SpyClient(world)
    live = LiveStreamingSession(
        client, NS, k=3, use_watch=False, topology_check_every=100,
        use_columnar=True,
    )
    client.calls = {k: 0 for k in client.calls}
    out = live.poll()
    assert "quiet" in out and out["quiet"] is False
    assert client.calls["get_pods"] == 0
    assert client.calls["get_events"] == 0


def test_patched_session_matches_fresh_session_property():
    """Property: after ANY sequence of journaled mutations, the
    watch-patched session's ranking equals a session built fresh from a
    full capture of the same world — the patch path may skip work, never
    change results.  Randomized ops cover pod status flips, metric
    changes, trace error-rate changes, log rewrites, and service
    additions (which must resync)."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.world import (
        make_deployment,
        make_service,
        waiting_status,
    )

    rng = np.random.default_rng(5)
    ns = "propwatch"
    # ≤ 25 pods: the snapshot's healthy-pod log-sampling cap
    # (_prioritize_pods_for_logs) never binds, so a fresh capture is a
    # complete oracle — above the cap, WHICH healthy pods get logs depends
    # on how many are unhealthy at capture time, and two captures of the
    # same world can legitimately differ (sampling artifact, not a patch
    # bug; see LiveStreamingSession's docstring)
    world = synthetic_cascade_world(20, n_roots=1, seed=9, namespace=ns)
    client = MockClusterClient(world)
    live = LiveStreamingSession(client, ns, k=5, topology_check_every=10_000)

    import copy

    def mutate_once(step: int) -> None:
        op = rng.integers(0, 5)
        if op == 0:  # pod goes crashloop / heals
            # mutate by REPLACEMENT, not in place: the session's retained
            # snapshot aliases the world's dict objects (shallow list
            # copies + copy-on-write sanitize), so an in-place edit would
            # leak into the stale snapshot and make this property test
            # vacuous for the pod-refetch path (review-caught: with
            # aliasing, deleting the refetch entirely still passed)
            idx = int(rng.integers(0, len(world.pods[ns])))
            pod = copy.deepcopy(world.pods[ns][idx])
            app = pod["metadata"]["labels"].get("app", "x")
            if rng.random() < 0.5:
                pod["status"]["phase"] = "Running"
                pod["status"]["containerStatuses"] = [waiting_status(
                    app, "CrashLoopBackOff",
                    restarts=int(rng.integers(1, 9)), last_exit_code=1,
                )]
            else:
                pod["status"]["containerStatuses"] = [{
                    "name": app, "ready": True, "restartCount": 0,
                    "state": {"running": {}},
                }]
            world.pods[ns][idx] = pod
            world.touch("pod", ns, pod["metadata"]["name"])
        elif op == 1:  # metrics spike (replacement for the same reason)
            pods = world.pod_metrics[ns]["pods"]
            name = list(pods)[int(rng.integers(0, len(pods)))]
            rec = copy.deepcopy(pods[name])
            rec["cpu"]["usage_percentage"] = float(rng.uniform(10, 99))
            pods[name] = rec
            world.touch("pod_metrics", ns, name)
        elif op == 2:  # trace error-rate change
            ers = world.traces["error_rates"][ns]
            svc = list(ers)[int(rng.integers(0, len(ers)))]
            ers[svc] = round(float(rng.uniform(0, 0.9)), 3)
            world.touch("traces", ns, svc)
        elif op == 3:  # log content changes
            logs = world.logs[ns]
            name = list(logs)[int(rng.integers(0, len(logs)))]
            container = next(iter(logs[name]))
            logs[name][container] = (
                "ERROR: connection refused\n" * int(rng.integers(1, 4))
            )
            world.touch("logs", ns, name)
        else:  # new service appears (topology kind -> resync)
            svc = f"newsvc-{step}"
            world.add("services", ns, make_service(svc, ns))
            world.add("deployments", ns, make_deployment(svc, ns, svc))

    for step in range(12):
        for _ in range(int(rng.integers(1, 4))):
            mutate_once(step)
        out = live.poll()
        # reuse the engine: oracle independence comes from the fresh
        # CAPTURE, not a fresh compile cache (tick results are stateless
        # functions of features+edges)
        fresh = LiveStreamingSession(
            client, ns, k=5, topology_check_every=10_000, use_watch=False,
            engine=live.engine,
        )
        expected = fresh.poll()
        got_rank = [(r["component"], round(r["score"], 5))
                    for r in out["ranked"]]
        want_rank = [(r["component"], round(r["score"], 5))
                     for r in expected["ranked"]]
        assert got_rank == want_rank, (
            f"step {step}: patched session diverged from fresh capture\n"
            f"patched: {got_rank}\nfresh:   {want_rank}"
        )


# -- live watch pumps (stub kubernetes module) -------------------------------

class _Meta:
    def __init__(self, name, rv=""):
        self.name = name
        self.resource_version = rv


class _Involved:
    def __init__(self, name):
        self.name = name


class _PodObj:
    def __init__(self, name, rv="101"):
        self.metadata = _Meta(name, rv)


class _EventObj:
    def __init__(self, involved, rv="201"):
        self.metadata = _Meta("evt-x", rv)
        self.involved_object = _Involved(involved)


class _BookmarkObj:
    def __init__(self, rv):
        self.metadata = _Meta("", rv)


def _install_kubernetes_stub(monkeypatch, pod_events, event_events,
                             die_after=False, seen_rvs=None):
    """Stub kubernetes.watch.Watch whose stream yields canned events once,
    then (optionally) raises like a 410, else blocks briefly forever.
    Records the resource_version each stream call resumed from in
    ``seen_rvs`` so tests can assert RV tracking (no-replay contract)."""
    mod = types.ModuleType("kubernetes")
    watch_mod = types.ModuleType("kubernetes.watch")

    class _Watch:
        def stream(self, list_fn, namespace=None, timeout_seconds=None,
                   resource_version=None, allow_watch_bookmarks=None):
            if seen_rvs is not None:
                seen_rvs.append(resource_version)
            batch = pod_events if "pod" in list_fn.__name__ else event_events
            yield from batch
            batch.clear()  # second stream round yields nothing
            if die_after:
                raise RuntimeError("Expired: too old resource version (410)")
            time.sleep(0.05)

        def stop(self):
            pass

    watch_mod.Watch = _Watch
    mod.watch = watch_mod
    monkeypatch.setitem(sys.modules, "kubernetes", mod)
    monkeypatch.setitem(sys.modules, "kubernetes.watch", watch_mod)


class _ListResp:
    def __init__(self, rv):
        self.metadata = _Meta("", rv)
        self.items = []


class _FakeCore:
    """The initial limit=1 list returns the collection RV the pump must
    pin its first stream to."""

    def list_namespaced_pod(self, *a, **k):
        return _ListResp("100")

    def list_namespaced_event(self, *a, **k):
        return _ListResp("200")


def _wait_until(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_watch_pumps_queue_changes(monkeypatch):
    _install_kubernetes_stub(
        monkeypatch,
        pod_events=[{"type": "ADDED", "object": _PodObj("db-0")},
                    {"type": "MODIFIED", "object": _PodObj("db-0")},
                    {"type": "ADDED", "object": _PodObj("web-1")}],
        event_events=[{"type": "ADDED", "object": _EventObj("db-0")}],
    )
    from rca_tpu.cluster.watch_pump import WatchPumpSet

    pumps = WatchPumpSet(_FakeCore(), "prod")
    token = pumps.register()
    pumps.start()
    try:
        assert _wait_until(lambda: len(pumps._journal) >= 3)
        changes = pumps.drain(token)
        # dedup within a drain; involved-object name extracted from events
        assert {(c["kind"], c["name"]) for c in changes} == {
            ("pod", "db-0"), ("pod", "web-1"), ("event", "db-0"),
        }
        assert not pumps.expired
    finally:
        pumps.stop()


def test_watch_pump_tracks_resource_version(monkeypatch):
    """The no-replay contract: the first stream resumes from the initial
    list's collection RV, later streams from the last event/bookmark RV —
    otherwise every 30 s renewal replays the whole collection and a 10k
    namespace overflows the queue into a permanent resync loop."""
    seen_rvs = []
    _install_kubernetes_stub(
        monkeypatch,
        pod_events=[{"type": "MODIFIED", "object": _PodObj("db-0", rv="150")},
                    {"type": "BOOKMARK", "object": _BookmarkObj("175")}],
        event_events=[],
        seen_rvs=seen_rvs,
    )
    from rca_tpu.cluster.watch_pump import WatchPumpSet

    pumps = WatchPumpSet(_FakeCore(), "prod")
    token = pumps.register()
    pumps.start()
    try:
        # both pumps opened (RVs 100/200 from the initial lists), then the
        # pod pump renewed at the bookmark RV after draining its batch
        assert _wait_until(lambda: "175" in seen_rvs)
        assert "100" in seen_rvs and "200" in seen_rvs
        # bookmark events advance RV but enqueue nothing
        assert {(c["kind"], c["name"]) for c in pumps.drain(token)} == {
            ("pod", "db-0"),
        }
    finally:
        pumps.stop()


def test_watch_pump_error_marks_expired(monkeypatch):
    _install_kubernetes_stub(
        monkeypatch,
        pod_events=[{"type": "ADDED", "object": _PodObj("p")}],
        event_events=[],
        die_after=True,
    )
    from rca_tpu.cluster.watch_pump import WatchPumpSet

    pumps = WatchPumpSet(_FakeCore(), "prod")
    pumps.start()
    try:
        assert _wait_until(lambda: pumps.expired)
    finally:
        pumps.stop()


def test_k8s_client_watch_changes_lifecycle(monkeypatch):
    """Client-level feed contract over the stubbed SDK: open → drain →
    namespace isolation → expiry surfaces as expired=True with a fresh
    token to reopen against."""
    _install_kubernetes_stub(
        monkeypatch,
        pod_events=[{"type": "MODIFIED", "object": _PodObj("db-0")}],
        event_events=[],
    )
    import rca_tpu.cluster.k8s_client as kc
    from rca_tpu.cluster.k8s_client import K8sApiClient

    monkeypatch.setattr(kc, "HAVE_K8S_LIB", True)
    client = K8sApiClient.__new__(K8sApiClient)  # skip kubeconfig loading
    client._connected = True
    client._core = _FakeCore()
    client._errors = []
    client._kubectl = None
    client._kubeconfig = None

    try:
        head = client.watch_changes("prod", None)
        assert head["supported"] and not head["expired"]
        assert _wait_until(
            lambda: client.watch_changes("prod", head["cursor"])["changes"]
            or client._pumps["prod"].expired
        )
        # a second namespace opens its own pump set without touching prod's
        other = client.watch_changes("staging", None)
        assert other["cursor"] != head["cursor"]
        assert set(client._pumps) == {"prod", "staging"}
        again = client.watch_changes("prod", head["cursor"])
        assert not again["expired"]

        # stale/foreign cursor -> expired; caller reopens with cursor=None
        stale = client.watch_changes("prod", "pumps-does-not-exist")
        assert stale["expired"] is True
    finally:
        for pumps in getattr(client, "_pumps", {}).values():
            pumps.stop()


def test_pump_journal_overflow_expires_only_laggards():
    """A consumer that falls behind the journal window expires
    INDIVIDUALLY; the pump set and up-to-date consumers keep working."""
    from rca_tpu.cluster import watch_pump
    from rca_tpu.cluster.watch_pump import WatchPumpSet

    pumps = WatchPumpSet(_FakeCore(), "prod")  # never started: direct pushes
    laggard = pumps.register()
    for i in range(watch_pump.QUEUE_CAP + 10):
        pumps.push("pod", f"p{i}")
    fresh = pumps.register()
    pumps.push("pod", "after-registration")
    assert pumps.drain(laggard) is None        # lagged past the window
    assert not pumps.expired                   # the SET is still healthy
    assert pumps.drain(fresh) == [{"kind": "pod",
                                   "name": "after-registration"}]
    # the expired laggard was deregistered; its token stays expired
    assert pumps.drain(laggard) is None


def test_watch_close_releases_journal_pin():
    """An abandoned consumer token pins the journal trim floor;
    deregistering it (sessions do this via watch_close on resync) lets
    the window trim back down."""
    from rca_tpu.cluster.watch_pump import WatchPumpSet

    pumps = WatchPumpSet(_FakeCore(), "prod")
    a = pumps.register()
    b = pumps.register()
    for i in range(100):
        pumps.push("pod", f"p{i}")
    assert len(pumps.drain(b)) == 100    # b is caught up
    assert len(pumps._journal) == 100    # ...but a pins the floor
    pumps.deregister(a)
    assert len(pumps._journal) == 0      # trimmed to b's position
    pumps.push("pod", "next")
    assert pumps.drain(b) == [{"kind": "pod", "name": "next"}]


def test_two_consumers_share_one_namespace_feed(monkeypatch):
    """Round-3 advisor finding: two sessions on the SAME namespace must
    not thrash the feed — each holds its own token over one shared pump
    set, and a second open must not invalidate the first's cursor."""
    _install_kubernetes_stub(
        monkeypatch,
        pod_events=[{"type": "MODIFIED", "object": _PodObj("db-0")}],
        event_events=[],
    )
    import rca_tpu.cluster.k8s_client as kc
    from rca_tpu.cluster.k8s_client import K8sApiClient

    monkeypatch.setattr(kc, "HAVE_K8S_LIB", True)
    client = K8sApiClient.__new__(K8sApiClient)
    client._connected = True
    client._core = _FakeCore()
    client._errors = []
    client._kubectl = None
    client._kubeconfig = None

    try:
        a = client.watch_changes("prod", None)
        b = client.watch_changes("prod", None)  # second session, same ns
        assert a["cursor"] != b["cursor"]
        assert len(client._pumps) == 1          # ONE shared pump set
        # both drain the same change independently, neither expires
        pumps = client._pumps["prod"]
        assert _wait_until(lambda: pumps._next > 0)
        # a's drains advance ONLY a's position; b polling right after must
        # not read as expired (the old design replaced the set per opener,
        # so the other session degraded to a sweep+resync every poll)
        ra = client.watch_changes("prod", a["cursor"])
        rb = client.watch_changes("prod", b["cursor"])
        assert not ra["expired"] and not rb["expired"]
        ra2 = client.watch_changes("prod", a["cursor"])
        assert not ra2["expired"] and ra2["changes"] == []
    finally:
        for pumps in getattr(client, "_pumps", {}).values():
            pumps.stop()


def test_reconnect_tears_down_stale_pumps(monkeypatch):
    """Round-3 advisor finding (medium): rebuilding the connection
    (switch_context / reload_config / update_server_url all route through
    _connect) must stop and clear the pump sets so stale threads don't
    keep serving the OLD cluster's change feed with still-valid tokens."""
    _install_kubernetes_stub(
        monkeypatch,
        pod_events=[{"type": "MODIFIED", "object": _PodObj("db-0")}],
        event_events=[],
    )
    import rca_tpu.cluster.k8s_client as kc
    from rca_tpu.cluster.k8s_client import K8sApiClient

    monkeypatch.setattr(kc, "HAVE_K8S_LIB", True)
    client = K8sApiClient.__new__(K8sApiClient)
    client._connected = True
    client._core = _FakeCore()
    client._errors = []
    client._kubectl = None
    client._kubeconfig = None
    client._context = None
    client._verify_ssl = True

    head = client.watch_changes("prod", None)
    old_set = client._pumps["prod"]
    try:
        client._connect()  # stub lib has no config loader: reconnect fails
        # ...but the pumps are torn down and the registry cleared FIRST
        assert old_set._stop.is_set()
        assert client._pumps == {}
        # the old token can never silently re-attach: once reconnected,
        # draining it reports expired (forcing the session to resync
        # against the new cluster)
        client._connected = True
        client._core = _FakeCore()
        stale = client.watch_changes("prod", head["cursor"])
        assert stale["expired"] is True
    finally:
        for pumps in getattr(client, "_pumps", {}).values():
            pumps.stop()


def test_pump_stop_breaks_stream_promptly(monkeypatch):
    """Round-3 advisor finding: stop() must call watch.Watch.stop() on
    each pump's stream handle, not just set the event, so streams end at
    their next delivered event instead of looping into another renewal
    (best-effort: the real client can still block in a quiet HTTP read
    until the 30 s server-side close — bounded and harmless)."""
    stopped = []

    mod = types.ModuleType("kubernetes")
    watch_mod = types.ModuleType("kubernetes.watch")

    class _BlockingWatch:
        def stream(self, list_fn, **kwargs):
            while True:  # emits nothing; only stop() can break the loop
                if self._stopped:
                    return
                time.sleep(0.01)
                yield from ()

        def __init__(self):
            self._stopped = False

        def stop(self):
            self._stopped = True
            stopped.append(self)

    watch_mod.Watch = _BlockingWatch
    mod.watch = watch_mod
    monkeypatch.setitem(sys.modules, "kubernetes", mod)
    monkeypatch.setitem(sys.modules, "kubernetes.watch", watch_mod)

    from rca_tpu.cluster.watch_pump import WatchPumpSet

    pumps = WatchPumpSet(_FakeCore(), "prod")
    pumps.start()
    assert _wait_until(
        lambda: all(t.watch_handle is not None for t in pumps._threads)
    )
    pumps.stop()
    assert len(stopped) >= 2  # both pumps' streams were broken
    for t in pumps._threads:
        t.join(timeout=5)
        assert not t.is_alive()
    # a teardown-induced stream break is a shutdown, not a 410
    assert not pumps.expired


def test_partial_sweep_schedules_recovery_resync():
    """Round-3 advisor finding: the periodic topology check drains the
    feed and discards its changes in favor of the sweep — if that sweep's
    capture comes back PARTIAL (snapshot errors), the discarded
    notifications may describe exactly the objects the capture missed, so
    the next poll must resync rather than serve stale rows."""
    world = five_service_world()

    class FlakyClient(MockClusterClient):
        inject = False

        def collect_errors(self, clear=True):
            if self.inject:
                return [{"op": "list_namespaced_pod", "error": "boom"}]
            return []

    client = FlakyClient(world)
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=3)
    assert live.resyncs == 0
    assert live.poll()["quiet"] is True      # poll 1
    assert live.poll()["quiet"] is True      # poll 2
    client.inject = True
    out = live.poll()                # poll 3: periodic sweep, PARTIAL
    assert out["resynced"] is False
    assert live._pending_resync is True
    client.inject = False
    out2 = live.poll()               # poll 4: recovery resync
    assert out2["resynced"] is True
    out3 = live.poll()               # poll 5: back to normal quiet polls
    assert out3["quiet"] is True


def test_expiry_recovery_rejects_degraded_fetch():
    """Round-4 review finding: an API flake during expiry recovery must
    not read as mass pod deletion — the recovery aborts, keeps the
    retained state, and schedules a full resync instead of wiping the
    ranking."""
    world = five_service_world()
    world.journal_cap = 5

    class FlakyClient(MockClusterClient):
        flake = False

        def get_pods(self, namespace):
            if self.flake:
                return []          # what a swallowed API error looks like
            return super().get_pods(namespace)

        def collect_errors(self, clear=True):
            if self.flake:
                return [{"op": "list_namespaced_pod", "error": "boom"}]
            return []

    client = FlakyClient(world)
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=10_000)
    baseline = [r["component"] for r in live.poll()["ranked"]]
    for i in range(20):
        world.touch("pod", NS, f"ghost-{i}")  # trim past the cursor
    client.flake = True
    out = live.poll()
    assert out.get("recovered") is False
    assert live._pending_resync is True
    assert len(live._names) == len(baseline) or live._names  # state kept
    assert [r["component"] for r in out["ranked"]] == baseline
    client.flake = False
    out2 = live.poll()                       # scheduled recovery resync
    assert out2["resynced"] is True
    assert [r["component"] for r in out2["ranked"]] == baseline
