"""ISSUE 2: pipelined streaming ticks + incremental capture cache.

Covers the round-6 contracts:

- dispatch/fetch split is bit-identical to the old one-call tick;
- ``pipeline_depth=2`` rankings are EXACTLY the serial sequence delivered
  one tick late (60-tick seeded run, including periodic sweep polls);
- under ChaosClusterClient faults the pipeline never raises and the
  degradation ladder drains/flushes the in-flight tick cleanly;
- the incremental feature cache matches full re-extraction after
  arbitrary update/delete sequences (property test);
- tools/lint_tick_sync.py gates the no-sync-outside-fetch invariant.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from rca_tpu.cluster.generator import (
    synthetic_cascade_arrays,
    synthetic_cascade_world,
)
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.cluster.world import make_event, waiting_status
from rca_tpu.engine import GraphEngine, LiveStreamingSession
from rca_tpu.engine.streaming import StreamingSession
from rca_tpu.features.extract import IncrementalExtractor, extract_features


def _ranked_key(out):
    return json.dumps(out["ranked"], sort_keys=True)


# -- dispatch/fetch split ----------------------------------------------------

def test_dispatch_fetch_equals_tick():
    """fetch(dispatch()) IS tick(): same rankings, scores, upload
    accounting — the serial path through the split is bit-identical."""
    c = synthetic_cascade_arrays(300, n_roots=1, seed=5)

    def session():
        s = StreamingSession(
            c.names, c.dep_src, c.dep_dst,
            num_features=c.features.shape[1], k=3,
        )
        s.set_all(c.features)
        return s

    a, b = session(), session()
    for t in range(4):
        rows = {
            int((c.roots[0] + 17 * t + j) % c.n): np.full(
                c.features.shape[1], 0.3 + 0.1 * t, np.float32
            )
            for j in range(3)
        }
        a.update_many(rows)
        b.update_many(rows)
        out_a = a.tick()
        h = b.dispatch()
        out_b = b.fetch(h)
        assert _ranked_key(out_a) == _ranked_key(out_b)
        assert out_a["upload_rows"] == out_b["upload_rows"]
        assert out_a["sanitized_rows"] == out_b["sanitized_rows"]
        assert out_b["dispatch_ms"] >= 0 and out_b["fetch_ms"] >= 0


def test_streaming_session_manual_pipeline_shifted_parity():
    """Depth-2 by hand on the raw session: dispatch N, stage N+1, fetch N
    — the fetched sequence equals the serial sequence exactly."""
    c = synthetic_cascade_arrays(400, n_roots=2, seed=9)
    deltas = []
    rng = np.random.default_rng(3)
    for _ in range(6):
        deltas.append({
            int(i): np.clip(
                c.features[i] + rng.uniform(-0.2, 0.2, c.features.shape[1]),
                0, 1,
            ).astype(np.float32)
            for i in rng.integers(0, c.n, 5)
        })

    def session():
        s = StreamingSession(
            c.names, c.dep_src, c.dep_dst,
            num_features=c.features.shape[1], k=4,
        )
        s.set_all(c.features)
        s.tick()
        return s

    serial = session()
    serial_seq = []
    for rows in deltas:
        serial.update_many(rows)
        serial_seq.append(_ranked_key(serial.tick()))

    piped = session()
    piped_seq = []
    prev = None
    for rows in deltas:
        piped.update_many(rows)
        h = piped.dispatch()
        if prev is not None:
            piped_seq.append(_ranked_key(piped.fetch(prev)))
        prev = h
    piped_seq.append(_ranked_key(piped.fetch(prev)))
    assert piped_seq == serial_seq


# -- live session pipeline ---------------------------------------------------

def _mutate(world, ns, op):
    """Apply one descriptor-driven mutation (same descriptor applied to
    twin worlds keeps them bit-identical)."""
    kind, idx, arg = op
    pods = world.pods[ns]
    pod = pods[idx % len(pods)]
    name = pod["metadata"]["name"]
    app = pod["metadata"]["labels"].get("app", "x")
    if kind == "crash":
        pod["status"]["phase"] = "Running"
        pod["status"]["containerStatuses"] = [
            waiting_status(app, "CrashLoopBackOff",
                           restarts=arg, last_exit_code=1)
        ]
        world.touch("pod", ns, name)
    elif kind == "heal":
        pod["status"]["phase"] = "Running"
        pod["status"]["containerStatuses"] = [
            {"name": app, "ready": True, "restartCount": 0,
             "state": {"running": {}}}
        ]
        world.touch("pod", ns, name)
    elif kind == "logs":
        world.logs[ns][name] = {app: f"ERROR: failure mode {arg}\n" * arg}
        world.touch("logs", ns, name)
    elif kind == "metrics":
        rec = world.pod_metrics[ns]["pods"].get(name)
        if rec:
            rec["cpu"]["usage_percentage"] = float(arg)
            world.touch("pod_metrics", ns, name)


def _op_sequence(seed, n):
    rng = np.random.default_rng(seed)
    kinds = ("crash", "heal", "logs", "metrics")
    return [
        (kinds[int(rng.integers(0, len(kinds)))],
         int(rng.integers(0, 10_000)), int(rng.integers(1, 9)))
        for _ in range(n)
    ]


def test_live_pipeline_60_tick_bit_parity():
    """Acceptance gate: over a 60-tick seeded run (busy polls, quiet
    polls, periodic sweep polls), depth-2 rankings are EXACTLY the serial
    depth-1 sequence one tick late, with the first poll a pipeline-fill
    tick."""
    ops = _op_sequence(seed=13, n=60)

    def run(depth):
        world = synthetic_cascade_world(40, n_roots=1, seed=3,
                                        namespace="pipe")
        live = LiveStreamingSession(
            MockClusterClient(world), "pipe", k=3, engine=GraphEngine(),
            topology_check_every=7, pipeline_depth=depth,
        )
        seq = []
        for t, op in enumerate(ops):
            if t % 3 != 2:          # every third poll stays quiet
                _mutate(world, "pipe", op)
            out = live.poll()
            assert out["degraded"] is False
            seq.append((_ranked_key(out), out["health"]))
        return seq

    serial = run(1)
    piped = run(2)
    assert piped[0][1]["pipeline_fill"] is True
    assert piped[0][1]["result_lag"] == 0
    for k in range(1, 60):
        assert piped[k][0] == serial[k - 1][0], f"tick {k} diverged"
        assert piped[k][1]["result_lag"] == 1
        assert piped[k][1]["pipeline_depth"] == 2
        assert piped[k][1]["inflight"] == 1
    # serial health record advertises the serial contract
    assert serial[5][1]["pipeline_depth"] == 1
    assert serial[5][1]["result_lag"] == 0
    # per-shape kernel attribution (the retired process-level
    # noisyor_path stamp is gone — ISSUE 14 satellite)
    assert serial[5][1]["kernel_path"] in (
        "xla", "pallas", "segscan", "quantized", "doubling",
    )


def test_live_pipeline_under_chaos_never_raises_and_drains():
    """RESILIENCE contract at depth 2: injected faults (timeouts,
    truncated lists, NaN metrics, feed expiry storms) never escape
    poll(), the in-flight queue stays bounded at depth-1, and the session
    keeps serving rankings."""
    from rca_tpu.resilience.chaos import ChaosClusterClient, ChaosConfig

    world = synthetic_cascade_world(40, n_roots=1, seed=5, namespace="cx")
    cfg = ChaosConfig(seed=11)
    cfg.enabled = False             # bootstrap capture runs fault-free
    chaos = ChaosClusterClient(MockClusterClient(world), cfg)
    live = LiveStreamingSession(
        chaos, "cx", k=3, engine=GraphEngine(),
        topology_check_every=5, pipeline_depth=2,
    )
    cfg.enabled = True
    ops = _op_sequence(seed=29, n=60)
    served = 0
    for op in ops:
        _mutate(world, "cx", op)
        out = live.poll()           # must never raise
        assert len(live._inflight) <= 1
        if out["ranked"]:
            served += 1
        h = out["health"]
        assert h["pipeline_depth"] == 2
        assert h["inflight"] == len(live._inflight)
    assert served > 30              # chaos degraded ticks, not the stream
    # drain at teardown: the remaining in-flight tick is fetchable
    if live._inflight:
        final = live._inflight[-1]
        assert final.session.fetch(final)["ranked"]


def test_pipeline_degradation_flushes_inflight_cleanly():
    """A repeatedly-failing dispatch steps the ladder; the queued
    in-flight tick from the broken engine is FLUSHED (counted in health),
    the rebuilt session answers within the same poll chain, and rankings
    recover."""
    world = synthetic_cascade_world(30, n_roots=1, seed=7, namespace="dg")
    live = LiveStreamingSession(
        MockClusterClient(world), "dg", k=3, engine=GraphEngine(),
        topology_check_every=100, pipeline_depth=2,
    )
    healthy = live.poll()           # fill tick: dispatch queued
    assert healthy["health"]["inflight"] == 1

    def boom():
        raise RuntimeError("device dispatch failed")

    live.session.dispatch = boom
    out = live.poll()
    assert out["degraded"] is True
    assert live.degradation == 1
    assert out["health"]["degradation_rung"] == "single-device"
    assert out["health"]["pipeline_flushed"] == 1   # old in-flight dropped
    # the rebuilt session's dispatch queued a fresh tick
    assert out["health"]["inflight"] == 1
    # next polls serve the rebuilt engine's (identical) rankings; the
    # ladder is STICKY (matching the serial contract), so the tick stays
    # flagged degraded while running on the downgraded rung
    out2 = live.poll()
    assert out2["health"]["degradation_rung"] == "single-device"
    assert out2["ranked"]
    ref = LiveStreamingSession(
        MockClusterClient(synthetic_cascade_world(
            30, n_roots=1, seed=7, namespace="dg")),
        "dg", k=3, engine=GraphEngine(), topology_check_every=100,
    ).poll()
    assert _ranked_key(out2) == _ranked_key(ref)


def test_pipeline_depth_env_parsing(monkeypatch):
    from rca_tpu.config import pipeline_depth_from_env

    monkeypatch.delenv("RCA_PIPELINE_DEPTH", raising=False)
    assert pipeline_depth_from_env() == 1
    monkeypatch.setenv("RCA_PIPELINE_DEPTH", "2")
    assert pipeline_depth_from_env() == 2
    world = synthetic_cascade_world(20, n_roots=1, seed=1, namespace="e")
    live = LiveStreamingSession(
        MockClusterClient(world), "e", k=3, engine=GraphEngine(),
    )
    assert live.pipeline_depth == 2
    monkeypatch.setenv("RCA_PIPELINE_DEPTH", "0")
    with pytest.raises(ValueError):
        pipeline_depth_from_env()
    monkeypatch.setenv("RCA_PIPELINE_DEPTH", "fast")
    with pytest.raises(ValueError):
        pipeline_depth_from_env()


# -- incremental capture cache ----------------------------------------------

def test_incremental_extractor_property_update_delete():
    """Property: after ARBITRARY update/delete/add sequences, the
    incremental extraction over the persistent cache equals a fresh full
    extraction bit-for-bit (NaN rows included — poisoned telemetry must
    flow through identically)."""
    ns = "inc"
    world = synthetic_cascade_world(30, n_roots=1, seed=2, namespace=ns)
    client = MockClusterClient(world)
    inc = IncrementalExtractor()
    rng = np.random.default_rng(17)

    def rand_pod():
        pods = world.pods[ns]
        return pods[int(rng.integers(0, len(pods)))]

    for step in range(40):
        roll = int(rng.integers(0, 7))
        if roll <= 3:
            _mutate(world, ns, ("crash", int(rng.integers(0, 10_000)),
                                int(rng.integers(1, 9))))
        elif roll == 4:   # delete a pod
            pods = world.pods[ns]
            if len(pods) > 5:
                pod = pods.pop(int(rng.integers(0, len(pods))))
                name = pod["metadata"]["name"]
                world.logs[ns].pop(name, None)
                world.pod_metrics[ns]["pods"].pop(name, None)
                world.touch("pod", ns, name)
        elif roll == 5:   # poison a metric channel (NaN path)
            pod = rand_pod()
            rec = world.pod_metrics[ns]["pods"].get(
                pod["metadata"]["name"])
            if rec:
                rec["memory"]["usage_percentage"] = float("nan")
                world.touch("pod_metrics", ns,
                            pod["metadata"]["name"])
        else:             # warning event lands on a pod
            pod = rand_pod()
            world.add("events", ns, make_event(
                ns, "Pod", pod["metadata"]["name"], "BackOff",
                "Back-off restarting failed container",
                count=int(rng.integers(1, 5)),
            ))
        if step % 4 != 3:
            continue
        snap = ClusterSnapshot.capture(client, ns)
        got = inc.extract(snap, incremental=True)
        want = extract_features(snap)
        assert got.service_names == want.service_names
        assert got.pod_names == want.pod_names
        assert np.array_equal(got.pod_features, want.pod_features,
                              equal_nan=True)
        assert np.array_equal(got.service_features, want.service_features,
                              equal_nan=True)
        assert np.array_equal(got.pod_service, want.pod_service)
        assert np.array_equal(got.memb_pod, want.memb_pod)
        assert np.array_equal(got.memb_svc, want.memb_svc)
        assert np.array_equal(got.pod_node, want.pod_node)
        if step % 8 == 3:
            # interleave a full-mode pass (what a periodic sweep runs) —
            # it must also match and must refresh, not poison, the cache
            full = inc.extract(snap, incremental=False)
            assert np.array_equal(
                full.service_features, want.service_features,
                equal_nan=True,
            )


def test_incremental_extractor_reuses_cached_rows():
    """The cache actually caches: an unchanged capture re-derives zero
    rows (log regex scans skipped), a one-pod change re-derives one."""
    from rca_tpu.features import extract as ex

    ns = "hot"
    world = synthetic_cascade_world(25, n_roots=1, seed=4, namespace=ns)
    client = MockClusterClient(world)
    inc = IncrementalExtractor()
    # this test pins the DICT row cache (columnar captures skip it —
    # their rows assemble from columns; see tests/test_columnar.py)
    snap = ClusterSnapshot.capture(client, ns, columnar=False)
    inc.extract(snap)

    calls = []
    orig = ex.scan_pod_logs

    def counting(logs):
        calls.append(1)
        return orig(logs)

    ex.scan_pod_logs = counting
    try:
        inc.extract(ClusterSnapshot.capture(client, ns, columnar=False))
        assert not calls    # quiet capture: every row + log scan cached
        # mutate the logs of a pod that IS inside the snapshot's log
        # sample (capture caps healthy-pod log fetches)
        name = sorted(snap.logs)[0]
        app = name.rsplit("-", 1)[0]
        world.logs[ns][name] = {app: "ERROR: fresh failure\n" * 4}
        world.touch("logs", ns, name)
        inc.extract(ClusterSnapshot.capture(client, ns, columnar=False))
        assert len(calls) == 1   # exactly the touched pod re-scanned
    finally:
        ex.scan_pod_logs = orig


def test_sharded_session_pipelined_shifted_parity():
    """The sharded twin honors the same dispatch/fetch contract: a depth-2
    hand-rolled pipeline over the sp-sharded session returns exactly the
    serial tick sequence (the 50k bench dryrun runs this at scale)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from rca_tpu.engine import ShardedGraphEngine
    from rca_tpu.parallel.streaming import ShardedStreamingSession

    c = synthetic_cascade_arrays(512, n_roots=1, seed=6)
    names = [f"s{i}" for i in range(c.n)]

    def session():
        s = ShardedStreamingSession(
            names, c.dep_src, c.dep_dst, c.features.shape[1],
            engine=ShardedGraphEngine(spec="sp=8"), k=4,
        )
        s.set_all(c.features)
        s.tick()
        return s

    rng = np.random.default_rng(8)
    deltas = [{
        int(i): np.clip(
            c.features[i] + rng.uniform(0, 0.4, c.features.shape[1]), 0, 1
        ).astype(np.float32)
        for i in rng.integers(0, c.n, 5)
    } for _ in range(4)]

    serial = session()
    serial_seq = []
    for rows in deltas:
        serial.update_many(rows)
        serial_seq.append(_ranked_key(serial.tick()))

    piped = session()
    piped_seq = []
    prev = None
    for rows in deltas:
        piped.update_many(rows)
        h = piped.dispatch()
        if prev is not None:
            piped_seq.append(_ranked_key(piped.fetch(prev)))
        prev = h
    piped_seq.append(_ranked_key(piped.fetch(prev)))
    assert piped_seq == serial_seq


# -- lint gate ---------------------------------------------------------------

def test_tick_sync_lint_is_clean():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "lint_tick_sync.py")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- satellites: compile cache + autotune ------------------------------------

def test_compile_cache_status_flags(tmp_path, monkeypatch):
    import jax

    from rca_tpu import config as cfg

    monkeypatch.setattr(cfg, "_COMPILE_CACHE", None)
    monkeypatch.delenv("RCA_COMPILE_CACHE", raising=False)
    assert cfg.enable_compile_cache() == {"enabled": False}

    cache_dir = str(tmp_path / "xla-cache")
    monkeypatch.setattr(cfg, "_COMPILE_CACHE", None)
    monkeypatch.setenv("RCA_COMPILE_CACHE", cache_dir)
    try:
        status = cfg.enable_compile_cache()
        if status.get("enabled"):
            assert status["dir"] == cache_dir
            assert status["entries"] == 0
            assert jax.config.jax_compilation_cache_dir == cache_dir
        else:
            # a jax build without the knob records WHY instead of crashing
            assert "error" in status or status == {"enabled": False}
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        monkeypatch.setattr(cfg, "_COMPILE_CACHE", None)


def test_noisyor_autotune_cpu_picks_xla(monkeypatch):
    from rca_tpu.engine import pallas_kernels as pk

    try:
        monkeypatch.setenv("RCA_PALLAS", "0")
        assert pk.noisyor_autotune(refresh=True) == "xla"
        monkeypatch.delenv("RCA_PALLAS")
        # CPU backend short-circuits to XLA without timing an interpreter
        assert pk.noisyor_autotune(refresh=True) == "xla"
        assert pk.noisyor_path() == "xla"
    finally:
        monkeypatch.undo()
        pk.noisyor_autotune(refresh=True)
