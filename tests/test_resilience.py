"""Resilience layer: policies (hermetic clocks), chaos client parity,
NaN/Inf finite-mask sanitization, degradation ladder, resync-cause split,
breaker-gated LLM rotation, watch-pump stream-reopen retry, the
swallowed-fault lint, and a fast seeded chaos soak."""

from __future__ import annotations

import json
import subprocess
import sys
import time
import types

import numpy as np
import pytest

from rca_tpu.cluster.fixtures import NS, five_service_world
from rca_tpu.cluster.generator import (
    synthetic_cascade_arrays,
    synthetic_cascade_world,
)
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.engine import GraphEngine, LiveStreamingSession
from rca_tpu.features.schema import NUM_SERVICE_FEATURES
from rca_tpu.resilience.chaos import (
    FAULT_CLASSES,
    ChaosClusterClient,
    ChaosConfig,
    run_chaos_soak,
)
from rca_tpu.resilience.policy import (
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    Retry,
    drain_faults,
    suppressed,
)


# -- policy primitives (injectable time: no wall-clock in any test) ----------

def test_retry_backoff_sequence_and_attempt_cap():
    delays = []
    r = Retry(attempts=3, base_delay=1.0, max_delay=10.0, jitter=0.0,
              sleep=delays.append, seed=0)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ValueError("transient")
        return "ok"

    assert r.call(flaky) == "ok"
    assert delays == [1.0, 2.0]          # exponential, no jitter
    assert r.retries_spent == 2

    calls["n"] = 0
    r2 = Retry(attempts=1, base_delay=1.0, jitter=0.0,
               sleep=delays.append, seed=0)
    with pytest.raises(ValueError):
        r2.call(flaky)                   # 1 retry cannot cover 2 failures
    assert calls["n"] == 2


def test_retry_max_delay_and_jitter_bounds():
    r = Retry(attempts=8, base_delay=1.0, max_delay=4.0, jitter=0.25, seed=7)
    for attempt in range(1, 9):
        d = r.delay(attempt)
        assert 0.0 <= d <= 4.0 * 1.25


def test_retry_respects_deadline():
    t = [0.0]
    r = Retry(attempts=10, base_delay=5.0, jitter=0.0,
              sleep=lambda s: None, clock=lambda: t[0], seed=0)
    dl = Deadline(budget_s=3.0, clock=lambda: t[0])

    def always_fails():
        raise ValueError("nope")

    # the first retry's 5 s backoff cannot fit the 3 s budget
    with pytest.raises(DeadlineExceeded) as ei:
        r.call(always_fails, deadline=dl)
    assert isinstance(ei.value.__cause__, ValueError)


def test_circuit_breaker_state_machine():
    t = [0.0]
    cb = CircuitBreaker(failure_threshold=2, reset_after=10.0,
                        clock=lambda: t[0])
    assert cb.state == "closed" and cb.allow()
    cb.record_failure()
    assert cb.allow()                    # one failure: still closed
    cb.record_failure()
    assert cb.state == "open" and not cb.allow()
    t[0] = 10.0
    assert cb.allow()                    # half-open probe slot
    assert not cb.allow()                # only ONE probe at a time
    cb.record_failure()                  # probe failed: open again
    assert not cb.allow()
    t[0] = 20.0
    assert cb.allow()
    cb.record_success()
    assert cb.state == "closed" and cb.allow()


def test_suppressed_records_into_fault_log():
    drain_faults()
    with suppressed("test.op"):
        raise RuntimeError("swallowed but visible")
    faults = drain_faults()
    assert any(
        f["op"] == "test.op" and "swallowed but visible" in f["error"]
        for f in faults
    )
    with pytest.raises(KeyboardInterrupt):
        with suppressed("test.op2"):     # only Exception subclasses
            raise KeyboardInterrupt()


# -- chaos client: disabled == bit-identical passthrough ---------------------

def _soak_world():
    return synthetic_cascade_world(50, n_roots=1, seed=7,
                                   namespace="synthetic")


def test_chaos_disabled_is_bit_identical():
    """Property (satellite): with faults disabled the wrapper must be
    indistinguishable from the wrapped client — snapshot, change-feed
    journal, and findings JSON on the 50-service fixture."""
    plain = MockClusterClient(_soak_world())
    chaos = ChaosClusterClient(
        MockClusterClient(_soak_world()), ChaosConfig(seed=1, enabled=False)
    )
    snap_a = ClusterSnapshot.capture(plain, "synthetic")
    snap_b = ClusterSnapshot.capture(chaos, "synthetic")
    assert snap_a == snap_b

    # journal feed: identical cursor/changes through a mutation sequence
    ha = plain.watch_changes("synthetic", None)
    hb = chaos.watch_changes("synthetic", None)
    assert ha == hb
    for c in (plain, chaos):
        c.world.touch("pod", "synthetic", "p-x")
        c.world.touch("event", "synthetic", "p-x")
    assert (
        plain.watch_changes("synthetic", ha["cursor"])
        == chaos.watch_changes("synthetic", hb["cursor"])
    )

    engine = GraphEngine()
    ra = engine.analyze_snapshot(snap_a, k=5)
    rb = engine.analyze_snapshot(snap_b, k=5)
    assert json.dumps(ra.ranked, sort_keys=True) == json.dumps(
        rb.ranked, sort_keys=True
    )
    assert chaos.drain_injected() == []


def test_chaos_schedule_is_seed_deterministic():
    from rca_tpu.resilience.chaos import InjectedTimeout

    def injected_with(seed):
        chaos = ChaosClusterClient(
            MockClusterClient(_soak_world()), ChaosConfig(seed=seed)
        )
        for _ in range(50):
            for op in (chaos.get_pods, chaos.get_pod_metrics):
                try:
                    op("synthetic")
                except InjectedTimeout:
                    pass  # the injection itself is the signal under test
        return [f["fault"] for f in chaos.drain_injected()]

    assert injected_with(3) == injected_with(3)
    assert injected_with(3) != injected_with(4)


# -- finite-mask sanitizer ---------------------------------------------------

def test_nan_inf_each_channel_zeroes_only_poisoned_rows():
    """Satellite: poison each feature channel with NaN and Inf → the
    sanitizer zeroes exactly the poisoned rows (count reported) and the
    result is bit-identical to analyzing with those rows zeroed."""
    case = synthetic_cascade_arrays(50, n_roots=1, seed=0)
    engine = GraphEngine()
    rows = [3, 17]
    zeroed = case.features.copy()
    zeroed[rows] = 0.0
    ref = engine.analyze_arrays(
        zeroed, case.dep_src, case.dep_dst, case.names, k=5
    )
    assert ref.sanitized_rows == 0
    for poison in (np.nan, np.inf, -np.inf):
        for ch in range(NUM_SERVICE_FEATURES):
            f = case.features.copy()
            f[rows, ch] = poison
            out = engine.analyze_arrays(
                f, case.dep_src, case.dep_dst, case.names, k=5
            )
            assert out.sanitized_rows == len(rows)
            assert np.isfinite(out.score).all()
            np.testing.assert_array_equal(out.score, ref.score)
            assert json.dumps(out.ranked, sort_keys=True) == json.dumps(
                ref.ranked, sort_keys=True
            )


def test_ranking_over_clean_services_unchanged_by_poisoned_zeros():
    """Poisoning rows that carried no evidence anyway must leave the
    ranking EXACTLY equal to the fault-free run — the clean services'
    scores are untouched by the sanitizer."""
    case = synthetic_cascade_arrays(50, n_roots=1, seed=0)
    engine = GraphEngine()
    base_features = case.features.copy()
    rows = [5, 29]
    base_features[rows] = 0.0            # fault-free run: rows carry nothing
    base = engine.analyze_arrays(
        base_features, case.dep_src, case.dep_dst, case.names, k=5
    )
    poisoned = base_features.copy()
    poisoned[rows] = np.nan
    out = engine.analyze_arrays(
        poisoned, case.dep_src, case.dep_dst, case.names, k=5
    )
    assert out.sanitized_rows == len(rows)
    np.testing.assert_array_equal(out.score, base.score)
    assert [r["component"] for r in out.ranked] == [
        r["component"] for r in base.ranked
    ]


def test_streaming_tick_sanitizes_poisoned_delta_rows():
    from rca_tpu.engine.streaming import StreamingSession

    case = synthetic_cascade_arrays(30, n_roots=1, seed=1)
    names = list(case.names)
    sess = StreamingSession(names, case.dep_src, case.dep_dst,
                            num_features=case.features.shape[1],
                            engine=GraphEngine(), k=3)
    sess.set_all(case.features)
    out0 = sess.tick()
    assert out0["sanitized_rows"] == 0
    bad = case.features[2].copy()
    bad[0] = np.nan
    sess.update(2, bad)
    out1 = sess.tick()
    assert out1["sanitized_rows"] == 1
    # the poisoned row persisted as zeros: next tick is clean again
    out2 = sess.tick()
    assert out2["sanitized_rows"] == 0
    # and equals a session that uploaded zeros for that row directly
    sess2 = StreamingSession(names, case.dep_src, case.dep_dst,
                             num_features=case.features.shape[1],
                             engine=GraphEngine(), k=3)
    f2 = case.features.copy()
    f2[2] = 0.0
    sess2.set_all(f2)
    ref = sess2.tick()
    assert json.dumps(out2["ranked"], sort_keys=True) == json.dumps(
        ref["ranked"], sort_keys=True
    )


# -- live session: resync-cause split, never-raise poll, ladder --------------

def test_resync_cause_split_counters():
    from rca_tpu.cluster.world import make_deployment, make_service

    world = five_service_world()
    client = MockClusterClient(world)
    live = LiveStreamingSession(client, NS, k=3, engine=GraphEngine(),
                                topology_check_every=100)
    assert (live.resyncs_expired, live.resyncs_topology) == (0, 0)

    world.add("services", NS, make_service("brandnew", NS))
    world.add("deployments", NS, make_deployment("brandnew", NS, "brandnew"))
    out = live.poll()
    assert out["resynced"] is True
    assert out["health"]["resync_cause"] == "topology"
    assert (live.resyncs_expired, live.resyncs_topology) == (0, 1)

    live._pending_resync = True          # lost-notification recovery path
    out2 = live.poll()
    assert out2["resynced"] is True
    assert out2["health"]["resync_cause"] == "expired"
    assert (live.resyncs_expired, live.resyncs_topology) == (1, 1)
    assert live.resyncs == live.resyncs_expired + live.resyncs_topology


class _FlakyClient(MockClusterClient):
    """get_pods raises until ``heal()`` is called."""

    # getter-surface fault simulation: keep the columnar fast path off
    get_columnar = None

    def __init__(self, world):
        super().__init__(world)
        self.broken = False

    def get_pods(self, namespace):
        if self.broken:
            raise RuntimeError("api server unreachable")
        return super().get_pods(namespace)


def test_poll_never_raises_and_recovers():
    world = five_service_world()
    client = _FlakyClient(world)
    live = LiveStreamingSession(client, NS, k=3, engine=GraphEngine(),
                                topology_check_every=100)
    healthy = live.poll()
    assert healthy["degraded"] is False

    client.broken = True
    live._pending_resync = True          # forces a capture next poll
    out = live.poll()                    # capture raises internally
    assert out["degraded"] is True
    assert out["ranked"] == healthy["ranked"]   # stale but served
    assert any("live.poll" == f["op"] for f in out["health"]["faults"])

    client.broken = False
    out2 = live.poll()                   # pending resync recovers
    assert out2["degraded"] is False
    assert out2["resynced"] is True
    assert out2["health"]["resync_cause"] == "expired"
    assert json.dumps(out2["ranked"], sort_keys=True) == json.dumps(
        healthy["ranked"], sort_keys=True
    )


def test_degradation_ladder_steps_to_single_device():
    world = five_service_world()
    client = MockClusterClient(world)
    live = LiveStreamingSession(client, NS, k=3, engine=GraphEngine(),
                                topology_check_every=100)
    healthy = live.poll()

    def boom():
        raise RuntimeError("device dispatch failed")

    live.session.tick = boom             # kill the current session's tick
    out = live.poll()
    # two consecutive failures stepped the ladder; the rebuilt
    # single-device session answered within the same poll
    assert out["degraded"] is True
    assert live.degradation == 1
    assert out["health"]["degradation_rung"] == "single-device"
    assert json.dumps(out["ranked"], sort_keys=True) == json.dumps(
        healthy["ranked"], sort_keys=True
    )
    # subsequent polls stay on the (working) downgraded engine
    out2 = live.poll()
    assert out2["health"]["degradation_rung"] == "single-device"
    assert out2["ranked"] == healthy["ranked"]


# -- LLM: breaker-gated rotation ---------------------------------------------

class _QuotaProvider:
    name = "quota-prim"
    model = "m"

    def __init__(self):
        self.calls = 0

    def complete(self, messages, **kwargs):
        from rca_tpu.llm.providers import LLMQuotaExceeded

        self.calls += 1
        raise LLMQuotaExceeded("quota-prim: 429")


def test_breaker_gates_provider_rotation(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    monkeypatch.delenv("ANTHROPIC_API_KEY", raising=False)
    from rca_tpu.llm import LLMClient

    prim = _QuotaProvider()
    t = [0.0]
    llm = LLMClient(provider=prim, breakers={
        "quota-prim": CircuitBreaker(failure_threshold=1, reset_after=30.0,
                                     clock=lambda: t[0], name="quota-prim"),
    })
    assert llm.generate_completion("hi")         # rotated to offline
    assert llm.provider.name == "offline"
    assert prim.calls == 1

    # circuit open: switching back to the primary must NOT call it again
    llm.provider = prim
    assert llm.generate_completion("hi2")
    assert prim.calls == 1                        # breaker skipped the call

    # half-open after the reset window: the primary gets ONE probe
    llm.provider = prim
    t[0] = 30.0
    assert llm.generate_completion("hi3")
    assert prim.calls == 2


def test_rotation_exhaustion_chains_original_quota_error(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    monkeypatch.delenv("ANTHROPIC_API_KEY", raising=False)
    from rca_tpu.llm import LLMClient
    from rca_tpu.llm.providers import (
        LLMQuotaExceeded,
        LLMUnavailable,
        OfflineProvider,
    )

    def offline_dies(self, messages, **kwargs):
        raise LLMUnavailable("offline: simulated outage")

    monkeypatch.setattr(OfflineProvider, "complete", offline_dies)
    llm = LLMClient(provider=_QuotaProvider())
    with pytest.raises(LLMUnavailable) as ei:
        llm.generate_completion("hi")
    assert "quota-prim" in str(ei.value)
    assert isinstance(ei.value.__cause__, LLMQuotaExceeded)


def test_classify_error_names_the_provider():
    from rca_tpu.llm.providers import (
        LLMQuotaExceeded,
        _classify_error,
    )

    err = _classify_error(Exception("rate limit reached"), "openai")
    assert isinstance(err, LLMQuotaExceeded)
    assert str(err).startswith("openai: ")


# -- watch pump: transient stream errors retry before expiring ---------------

class _Meta:
    def __init__(self, name, rv=""):
        self.name = name
        self.resource_version = rv


class _PodObj:
    def __init__(self, name, rv="101"):
        self.metadata = _Meta(name, rv)


class _ListResp:
    def __init__(self, rv):
        self.metadata = _Meta("", rv)
        self.items = []


class _FakeCore:
    def list_namespaced_pod(self, *a, **k):
        return _ListResp("100")

    def list_namespaced_event(self, *a, **k):
        return _ListResp("200")


def _wait_until(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _install_flaky_kubernetes_stub(monkeypatch, fail_first_streams):
    """Watch stub whose pod stream raises a TRANSIENT error for the first
    ``fail_first_streams`` openings, then yields one pod event."""
    mod = types.ModuleType("kubernetes")
    watch_mod = types.ModuleType("kubernetes.watch")
    state = {"pod_fails": fail_first_streams, "delivered": False}

    class _Watch:
        def stream(self, list_fn, namespace=None, timeout_seconds=None,
                   resource_version=None, allow_watch_bookmarks=None):
            if "pod" in list_fn.__name__:
                if state["pod_fails"] > 0:
                    state["pod_fails"] -= 1
                    raise ConnectionError("connection reset by peer")
                if not state["delivered"]:
                    state["delivered"] = True
                    yield {"type": "ADDED", "object": _PodObj("db-0")}
            time.sleep(0.05)

        def stop(self):
            pass

    watch_mod.Watch = _Watch
    mod.watch = watch_mod
    monkeypatch.setitem(sys.modules, "kubernetes", mod)
    monkeypatch.setitem(sys.modules, "kubernetes.watch", watch_mod)


def test_pump_retries_transient_stream_error(monkeypatch):
    _install_flaky_kubernetes_stub(monkeypatch, fail_first_streams=2)
    from rca_tpu.cluster.watch_pump import WatchPumpSet

    retry = Retry(attempts=3, base_delay=0.0, jitter=0.0,
                  sleep=lambda s: None, seed=0)
    pumps = WatchPumpSet(_FakeCore(), "prod", retry=retry)
    token = pumps.register()
    pumps.start()
    try:
        assert _wait_until(lambda: len(pumps._journal) >= 1)
        assert not pumps.expired         # transient errors did NOT expire
        assert retry.retries_spent >= 2
        assert {(c["kind"], c["name"]) for c in pumps.drain(token)} == {
            ("pod", "db-0"),
        }
    finally:
        pumps.stop()


def test_pump_gone_still_expires_immediately(monkeypatch):
    """A 410-shaped error must bypass the retry loop: the RV is dead and
    every consumer has to re-list."""
    mod = types.ModuleType("kubernetes")
    watch_mod = types.ModuleType("kubernetes.watch")

    class _Watch:
        def stream(self, *a, **k):
            raise RuntimeError("Expired: too old resource version (410)")
            yield  # pragma: no cover

        def stop(self):
            pass

    watch_mod.Watch = _Watch
    mod.watch = watch_mod
    monkeypatch.setitem(sys.modules, "kubernetes", mod)
    monkeypatch.setitem(sys.modules, "kubernetes.watch", watch_mod)
    from rca_tpu.cluster.watch_pump import WatchPumpSet

    retry = Retry(attempts=5, base_delay=0.0, jitter=0.0,
                  sleep=lambda s: None, seed=0)
    pumps = WatchPumpSet(_FakeCore(), "prod", retry=retry)
    pumps.start()
    try:
        assert _wait_until(lambda: pumps.expired)
        assert retry.retries_spent == 0  # no retries burned on a 410
    finally:
        pumps.stop()


# -- lint + soak -------------------------------------------------------------

def test_swallowed_fault_lint_is_clean():
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "lint_swallowed_faults.py")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_chaos_soak_contract_fast():
    """Seeded 60-tick soak on the 50-service fixture (the fast tier-1
    variant of ``python -m rca_tpu chaos``): zero uncaught exceptions,
    every fault class observed, fault-free ticks bit-identical to the
    fault-free baseline session."""
    summary = run_chaos_soak(
        _soak_world, "synthetic", seed=7, ticks=60,
        engine_factory=GraphEngine, config=ChaosConfig(seed=7),
    )
    assert summary["uncaught_exceptions"] == 0
    assert summary["all_classes_observed"], summary["faults_injected"]
    assert summary["parity_ok"]
    assert summary["parity_ticks_checked"] > 0
    assert summary["resyncs_expired"] > 0
    assert set(FAULT_CLASSES) == set(summary["faults_injected"])
