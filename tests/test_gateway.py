"""ISSUE 9: the wire front door + the replay-driven regression canary.

Covers the gateway contracts:

- wire codec: float32 → JSON → float32 is the identity (the parity
  argument), malformed bodies fail loudly, the status map is honest;
- config: ``RCA_GATEWAY_PORT`` / ``RCA_GATEWAY_MAX_BODY`` /
  ``RCA_CANARY_SAMPLE_RATE`` validation round trips;
- loopback round-trip BIT parity vs in-process ``ServeClient`` at
  concurrency 16 (in-process gateway, and a subprocess-spawned
  ``rca serve --listen`` — the acceptance gate);
- honest backpressure: queue_full→429 with Retry-After, shed→503,
  oversized body→413, malformed→400, unknown route→404;
- chunked streaming subscription drain + tenant filtering;
- replica-kill under wire load: every request gets a terminal HTTP
  answer, zero double completions;
- breaker-fed /healthz and the /metrics exposition;
- the ServeMetrics consistent-snapshot fix under ``RCA_RSAN=1``;
- the canary: self-parity on the current build, and a deliberately
  perturbed scoring config caught at the exact bisected tick (also via
  the ``rca canary`` CLI exit code).
"""

from __future__ import annotations

import http.client
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.config import (
    ServeConfig,
    canary_sample_rate,
    gateway_max_body,
    gateway_port,
)
from rca_tpu.engine.runner import GraphEngine
from rca_tpu.gateway import (
    GatewayClient,
    GatewayServer,
    TickHub,
    WireError,
    decode_analyze,
    encode_analyze,
    status_code_for,
)
from rca_tpu.serve import ServeClient, ServeLoop, ServePool, ServeRequest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def engine():
    return GraphEngine()


@pytest.fixture(scope="module")
def case():
    return synthetic_cascade_arrays(48, n_roots=1, seed=3)


def _req(tenant="t", n=8, k=3, seed=0, **kw) -> ServeRequest:
    rng = np.random.default_rng(seed)
    feats = rng.uniform(0, 1, (n, 4)).astype(np.float32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    return ServeRequest(
        tenant=tenant, features=feats, dep_src=src, dep_dst=dst, k=k, **kw
    )


# -- wire codec ---------------------------------------------------------------

def test_wire_roundtrip_is_float32_identity():
    rng = np.random.default_rng(0)
    feats = rng.uniform(0, 1, (17, 4)).astype(np.float32)
    body = json.loads(json.dumps(encode_analyze(
        feats, np.arange(16, dtype=np.int32),
        np.arange(1, 17, dtype=np.int32), names=[f"s{i}" for i in range(17)],
        tenant="t", k=3, deadline_ms=250.0,
    )))
    kwargs = decode_analyze(body)
    assert kwargs["features"].dtype == np.float32
    # the parity argument: float32 -> JSON -> float32 is bit-exact
    assert np.array_equal(kwargs["features"], feats)
    assert kwargs["tenant"] == "t" and kwargs["k"] == 3
    assert kwargs["deadline_ms"] == 250.0


def test_wire_header_tenant_wins_over_body():
    body = encode_analyze(np.zeros((2, 2), np.float32), [0], [1],
                          tenant="body-tenant")
    kwargs = decode_analyze(body, header_tenant="header-tenant")
    assert kwargs["tenant"] == "header-tenant"


@pytest.mark.parametrize("mutate,match", [
    (lambda b: b.pop("features"), "features"),
    (lambda b: b.update(features=[1, 2, 3]), "2-d"),
    (lambda b: b.update(dep_src=[0, 1]), "equal length"),
    (lambda b: b.update(priority="urgent"), "priority"),
    (lambda b: b.update(k=0), "'k'"),
    (lambda b: b.update(names="not-a-list"), "names"),
])
def test_wire_rejects_malformed(mutate, match):
    body = encode_analyze(np.zeros((2, 2), np.float32), [0], [1])
    mutate(body)
    with pytest.raises(WireError, match=match):
        decode_analyze(body)


def test_status_map_is_honest():
    assert status_code_for("ok") == (200, None)
    assert status_code_for("degraded") == (200, None)
    code, retry = status_code_for("queue_full")
    assert code == 429 and retry >= 1
    code, retry = status_code_for("shed")
    assert code == 503 and retry >= 1
    assert status_code_for("error")[0] == 500


# -- config knobs (satellite) -------------------------------------------------

def test_gateway_config_env_round_trip(monkeypatch):
    monkeypatch.setenv("RCA_GATEWAY_PORT", "9001")
    monkeypatch.setenv("RCA_GATEWAY_MAX_BODY", "65536")
    monkeypatch.setenv("RCA_CANARY_SAMPLE_RATE", "0.25")
    assert gateway_port() == 9001
    assert gateway_max_body() == 65536
    assert canary_sample_rate() == 0.25


def test_gateway_config_defaults(monkeypatch):
    for name in ("RCA_GATEWAY_PORT", "RCA_GATEWAY_MAX_BODY",
                 "RCA_CANARY_SAMPLE_RATE"):
        monkeypatch.delenv(name, raising=False)
    assert gateway_port() == 8321
    assert gateway_max_body() == 8 * 1024 * 1024
    assert canary_sample_rate() == 1.0


@pytest.mark.parametrize("name,bad", [
    ("RCA_GATEWAY_PORT", "70000"),
    ("RCA_GATEWAY_PORT", "abc"),
    ("RCA_GATEWAY_MAX_BODY", "10"),
    ("RCA_CANARY_SAMPLE_RATE", "1.5"),
    ("RCA_CANARY_SAMPLE_RATE", "often"),
])
def test_gateway_config_rejects_bad_env(monkeypatch, name, bad):
    monkeypatch.setenv(name, bad)
    with pytest.raises(ValueError):
        {"RCA_GATEWAY_PORT": gateway_port,
         "RCA_GATEWAY_MAX_BODY": gateway_max_body,
         "RCA_CANARY_SAMPLE_RATE": canary_sample_rate}[name]()


# -- loopback parity (the tentpole gate) -------------------------------------

def test_wire_parity_vs_inprocess_concurrency_16(engine, case):
    """Concurrency-16 loopback load: every wire ranking is bit-identical
    to the in-process ServeClient submission AND to a solo analysis."""
    rng = np.random.default_rng(1)
    feats = [
        np.clip(case.features + rng.uniform(
            0, 0.05, case.features.shape
        ).astype(np.float32), 0, 1)
        for _ in range(16)
    ]
    loop = ServeLoop(engine=engine).start()
    try:
        with GatewayServer(loop, port=0) as gw:
            cl = GatewayClient(gw.host, gw.port)
            wire: list = [None] * 16

            def worker(i: int) -> None:
                code, body, _ = cl.analyze(
                    feats[i], case.dep_src, case.dep_dst,
                    names=case.names, tenant=f"t{i % 4}", k=3,
                )
                wire[i] = (code, body)

            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(16)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            inproc = ServeClient(loop)
            for i, f in enumerate(feats):
                code, body = wire[i]
                assert code == 200 and body["status"] == "ok"
                assert body["degraded"] is False
                resp = inproc.analyze(
                    f, case.dep_src, case.dep_dst, names=case.names,
                    tenant="oracle", k=3,
                )
                assert body["ranked"] == resp.ranked
                solo = engine.analyze_arrays(
                    f, case.dep_src, case.dep_dst, case.names, k=3
                )
                assert body["ranked"] == solo.ranked
    finally:
        loop.stop()


# -- per-tenant rate limiting (ISSUE 10 satellite) ---------------------------

def test_tenant_rate_limiter_bucket_math():
    """Token-bucket unit contract on a fake clock: one second's burst,
    refill at rps, Retry-After = seconds until the next token, tenants
    independent."""
    from rca_tpu.gateway.server import TenantRateLimiter

    now = [100.0]
    lim = TenantRateLimiter(rps=2.0, clock=lambda: now[0])
    # burst = max(1, rps) = 2 tokens
    assert lim.admit("a") == 0.0
    assert lim.admit("a") == 0.0
    wait = lim.admit("a")
    assert wait > 0.0 and wait <= 0.5 + 1e-9
    # a different tenant has its own bucket
    assert lim.admit("b") == 0.0
    # refill: half a second buys one token at 2 rps
    now[0] += 0.5
    assert lim.admit("a") == 0.0
    assert lim.admit("a") > 0.0
    assert lim.rejected == 2


def test_tenant_rate_limiter_bounded_tenant_map():
    from rca_tpu.gateway.server import TenantRateLimiter

    now = [0.0]
    lim = TenantRateLimiter(rps=1.0, clock=lambda: now[0], max_tenants=4)
    for i in range(10):
        assert lim.admit(f"t{i}") == 0.0
    assert len(lim._buckets) <= 4


def test_gateway_tenant_rps_env_round_trip(monkeypatch):
    from rca_tpu.config import gateway_tenant_rps

    assert gateway_tenant_rps() == 0.0  # default: disabled
    monkeypatch.setenv("RCA_GATEWAY_TENANT_RPS", "2.5")
    assert gateway_tenant_rps() == 2.5
    monkeypatch.setenv("RCA_GATEWAY_TENANT_RPS", "-1")
    with pytest.raises(ValueError):
        gateway_tenant_rps()
    monkeypatch.setenv("RCA_GATEWAY_TENANT_RPS", "lots")
    with pytest.raises(ValueError):
        gateway_tenant_rps()


def test_gateway_rate_limits_hot_tenant_not_neighbors(engine, case):
    """A hot tenant burns its bucket and gets 429 + Retry-After WITHOUT
    touching the serve queue; a quiet tenant on the same gateway keeps
    getting 200s.  The /metrics exposition carries the rejection count."""
    loop = ServeLoop(engine=engine).start()
    frozen = [500.0]  # injectable clock: no refill mid-test
    try:
        with GatewayServer(loop, port=0, tenant_rps=2.0,
                           clock=lambda: frozen[0]) as gw:
            cl = GatewayClient(gw.host, gw.port)
            codes = []
            for _ in range(6):
                code, body, headers = cl.analyze(
                    case.features, case.dep_src, case.dep_dst,
                    names=case.names, tenant="hot", k=3,
                )
                codes.append(code)
                if code == 429:
                    assert body["status"] == "rate_limited"
                    assert "RCA_GATEWAY_TENANT_RPS" in body["detail"]
                    assert int(headers.get("Retry-After", 0)) >= 1
            assert codes.count(200) == 2      # exactly the burst
            assert codes.count(429) == 4
            # the quiet neighbor is unaffected
            code, body, _ = cl.analyze(
                case.features, case.dep_src, case.dep_dst,
                names=case.names, tenant="quiet", k=3,
            )
            assert code == 200 and body["status"] == "ok"
            # rejected requests never reached the scheduler
            summary = loop.metrics.summary()
            assert "hot" in summary.get("tenants", {})
            assert summary["tenants"]["hot"]["submitted"] == 2
            conn = http.client.HTTPConnection(gw.host, gw.port, timeout=10)
            try:
                conn.request("GET", "/metrics")
                text = conn.getresponse().read().decode()
            finally:
                conn.close()
            assert "rca_gateway_rate_limited_total 4" in text
    finally:
        loop.stop()


# -- honest backpressure ------------------------------------------------------

def test_backpressure_429_503_413_400_404(engine, case):
    """Queue at capacity → 429 + Retry-After; expired deadline → 503;
    oversized body → 413; malformed body → 400; unknown route → 404.
    The loop is deliberately NOT started, so the queue stays saturated
    and every outcome completes synchronously at admission."""
    loop = ServeLoop(engine=engine, config=ServeConfig(queue_cap=2))
    with GatewayServer(loop, port=0, max_body=256 * 1024) as gw:
        cl = GatewayClient(gw.host, gw.port)
        # saturate the queue in-process (these requests stay parked —
        # the loop never runs)
        for i in range(2):
            assert loop.submit(_req(seed=i))
        code, body, headers = cl.analyze(
            case.features, case.dep_src, case.dep_dst, k=3,
        )
        assert code == 429
        assert body["status"] == "queue_full"
        assert int(headers.get("Retry-After", 0)) >= 1
        # deadline already expired -> shed at admission -> 503
        code, body, headers = cl.analyze(
            case.features, case.dep_src, case.dep_dst, k=3,
            deadline_ms=-1.0,
        )
        assert code == 503
        assert body["status"] == "shed"
        assert int(headers.get("Retry-After", 0)) >= 1
        # oversized body refused before parsing
        big = np.zeros((600, 128), np.float32)
        code, body, _ = cl.analyze(big, [0], [1], k=3)
        assert code == 413
        assert "RCA_GATEWAY_MAX_BODY" in body["detail"]
        # malformed JSON -> 400; unknown route -> 404
        conn = http.client.HTTPConnection(gw.host, gw.port, timeout=10)
        try:
            conn.request("POST", "/v1/analyze", body=b"{not json",
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 400
            resp.read()
        finally:
            conn.close()
        conn = http.client.HTTPConnection(gw.host, gw.port, timeout=10)
        try:
            conn.request("GET", "/v1/nope")
            resp = conn.getresponse()
            assert resp.status == 404
            resp.read()
        finally:
            conn.close()
        snap = gw.metrics.snapshot()
        assert snap["body_rejections"] == 1
        assert snap["requests"][("analyze", 429)] == 1
        assert snap["requests"][("analyze", 503)] == 1


# -- streaming subscriptions --------------------------------------------------

def test_streaming_subscription_drain(engine, case):
    """Open a chunked subscription, serve N requests, and drain exactly
    the matching events (tenant filter included)."""
    loop = ServeLoop(engine=engine).start()
    try:
        with GatewayServer(loop, port=0) as gw:
            cl = GatewayClient(gw.host, gw.port)
            got: list = []
            ready = threading.Event()

            def subscriber() -> None:
                ready.set()
                for ev in cl.subscribe(tenant="watch-me", max_events=3,
                                       idle_s=20.0, timeout_s=60.0):
                    got.append(ev)

            t = threading.Thread(target=subscriber)
            t.start()
            ready.wait(10.0)
            # subscription registration races the first publish; wait
            # until the hub actually holds the subscriber
            for _ in range(100):
                if gw.hub.subscriber_count():
                    break
                threading.Event().wait(0.05)
            for i in range(3):
                code, _, _ = cl.analyze(
                    case.features, case.dep_src, case.dep_dst, k=3,
                    tenant="watch-me",
                )
                assert code == 200
                # an event for a DIFFERENT tenant must not reach this
                # subscriber
                cl.analyze(case.features, case.dep_src, case.dep_dst,
                           k=3, tenant="other")
            t.join(30.0)
            assert not t.is_alive()
            assert len(got) == 3
            assert all(ev["tenant"] == "watch-me" for ev in got)
            assert all(ev["status"] == "ok" for ev in got)
            assert gw.metrics.snapshot()["stream_events"] == 3
    finally:
        loop.stop()


def test_tickhub_slow_subscriber_drops_never_blocks():
    hub = TickHub()
    sid, q = hub.subscribe()
    for i in range(hub.QUEUE_CAP + 5):
        hub.publish({"tenant": "t", "i": i})
    assert q.qsize() == hub.QUEUE_CAP
    assert hub.dropped == 5
    hub.unsubscribe(sid)
    hub.publish({"tenant": "t"})   # no subscriber: no-op, no raise


# -- failover under wire load -------------------------------------------------

class _StubDispatcher:
    engine = None
    engine_tag = "stub"

    def __init__(self):
        self.graphs = set()

    def has_graph(self, key):
        return key in self.graphs

    def dispatch(self, batch, now=None):
        self.graphs.add(batch[0].graph_key)

        class _H:
            requests = list(batch)
            dispatched_at = now if now is not None else 0.0

        return _H()

    def fetch(self, handle):
        class _R:
            ranked = [{"component": "svc", "score": 1.0}]
            engine = "stub"
            score = np.ones(1, np.float32)

        return [_R() for _ in handle.requests]


def test_replica_kill_under_wire_load():
    """Kill a replica while wire load is in flight: every HTTP request
    gets a terminal answer (answered-or-shed as status codes) and
    completion stays exactly-once."""
    pool = ServePool(
        dispatchers=[_StubDispatcher() for _ in range(3)],
        config=ServeConfig(replicas=3, max_wait_us=0),
    ).start()
    try:
        with GatewayServer(pool, port=0) as gw:
            cl = GatewayClient(gw.host, gw.port, timeout_s=90.0)
            codes: list = []
            codes_lock = threading.Lock()

            def worker(w: int) -> None:
                rng = np.random.default_rng(w)
                for i in range(6):
                    feats = rng.uniform(
                        0, 1, (8 + 8 * (w % 2), 4)
                    ).astype(np.float32)
                    src = np.arange(feats.shape[0] - 1, dtype=np.int32)
                    dst = np.arange(1, feats.shape[0], dtype=np.int32)
                    if w == 0 and i == 3:
                        pool.replicas[0].kill()
                    code, body, _ = cl.analyze(
                        feats, src, dst, tenant=f"t{w % 3}", k=2,
                    )
                    with codes_lock:
                        codes.append((code, body["status"]))

            threads = [
                threading.Thread(target=worker, args=(w,))
                for w in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(codes) == 48
            # terminal, honest outcomes only — never a hang, never a 504
            assert all(code in (200, 500, 503) for code, _ in codes)
            assert pool.sink.double_completions == 0
            # the plane survived: most of the load was served
            assert sum(1 for code, _ in codes if code == 200) >= 40
            code, health = cl.healthz()
            assert code == 200   # survivors keep the plane routable
            assert health["replicas"]["0"] == "dead"
    finally:
        pool.stop()


# -- healthz + metrics --------------------------------------------------------

def test_healthz_maps_breaker_and_death(engine):
    import time as _time

    from rca_tpu.resilience.policy import CircuitBreaker

    loop = ServeLoop(engine=engine, breaker=CircuitBreaker(
        failure_threshold=3, reset_after=3600.0, clock=_time.monotonic,
        name="test.gateway.breaker",
    ))
    with GatewayServer(loop, port=0) as gw:
        cl = GatewayClient(gw.host, gw.port)
        code, health = cl.healthz()
        assert code == 200 and health["ok"] and health["breaker"] == "closed"
        # force the breaker open: health must go 503 (reset_after is an
        # hour, so the probe window cannot flip it back mid-test)
        for _ in range(5):
            loop.breaker.record_failure()
        code, health = cl.healthz()
        assert code == 503 and not health["ok"]
        assert health["breaker"] == "open"
    pool = ServePool(
        dispatchers=[_StubDispatcher() for _ in range(2)],
        config=ServeConfig(replicas=2, max_wait_us=0),
    )
    with GatewayServer(pool, port=0) as gw:
        cl = GatewayClient(gw.host, gw.port)
        assert cl.healthz()[0] == 200
        for r in pool.replicas:
            r.kill()
        code, health = cl.healthz()
        assert code == 503
        assert set(health["replicas"].values()) == {"dead"}


def test_metrics_endpoint_exports_tenant_and_replica_rows(case):
    pool = ServePool(
        dispatchers=[_StubDispatcher() for _ in range(2)],
        config=ServeConfig(replicas=2, max_wait_us=0),
    ).start()
    try:
        with GatewayServer(pool, port=0) as gw:
            cl = GatewayClient(gw.host, gw.port)
            for i in range(4):
                code, _, _ = cl.analyze(
                    case.features, case.dep_src, case.dep_dst,
                    tenant=f"tenant-{i % 2}", k=3,
                )
                assert code == 200
            text = cl.metrics_text()
            assert ('rca_serve_requests_total{outcome="answered",'
                    'tenant="tenant-0"}') in text
            assert 'rca_serve_replica_requests_total{replica="0"}' in text
            assert 'rca_serve_replica_state{replica="1"' in text
            assert ('rca_gateway_requests_total{code="200",'
                    'route="analyze"} 4') in text
            assert "rca_gateway_up 1" in text
    finally:
        pool.stop()


# -- ServeMetrics consistent snapshot under rsan (small fix) ------------------

def test_metrics_snapshot_consistent_under_rsan():
    """Regression for the ISSUE 9 small fix: 8 writer threads hammer
    every ServeMetrics surface while a reader snapshots concurrently —
    each snapshot must be internally CONSISTENT (the invariants that
    hold under the lock hold in the copy), rsan observes no races, and
    the metrics lock really was contended across threads."""
    from rca_tpu.analysis.concurrency import rsan
    from rca_tpu.serve.metrics import ServeMetrics

    was = rsan.enabled()
    rsan.enable()
    rsan.RSAN.reset()
    try:
        metrics = ServeMetrics()

        def writer(w: int) -> None:
            tenant = f"t{w % 3}"
            for i in range(300):
                metrics.submitted(tenant, i % 7)
                metrics.answered(tenant, float(i % 11))
                metrics.record_batch(1 + i % 4)
                metrics.replica_occupancy(w % 2, i % 5)
                metrics.replica_batch(w % 2, 1 + i % 4)
                if i % 50 == 0:
                    metrics.stolen(w % 2, (w + 1) % 2, 1)

        threads = [
            threading.Thread(target=writer, args=(w,)) for w in range(8)
        ]
        for t in threads:
            t.start()
        bad = []
        while any(t.is_alive() for t in threads):
            snap = metrics.snapshot()
            # invariants maintained under one lock must survive the copy
            if sum(snap["occupancy"]) != snap["dispatched_requests"]:
                bad.append("occupancy-vs-dispatched")
            for tenant, counts in snap["counts"].items():
                if snap["queue_ms"].count(tenant) != counts["answered"]:
                    bad.append(f"queue-samples-vs-answered:{tenant}")
            summary = metrics.summary()   # derives OFF-lock, no raise
            assert isinstance(summary["tenants"], dict)
        for t in threads:
            t.join()
        assert not bad, bad
        final = metrics.snapshot()
        assert sum(final["occupancy"]) == final["dispatched_requests"]
        assert final["dispatched_requests"] == 8 * sum(
            1 + i % 4 for i in range(300)
        )
        assert rsan.RSAN.races_observed() == []
        lt = rsan.RSAN.lock_threads()
        assert len(lt.get("ServeMetrics._lock", ())) >= 2
    finally:
        rsan.RSAN.reset()
        if not was:
            rsan.disable()


# -- canary -------------------------------------------------------------------

def test_canary_self_parity_and_store_refs(tmp_path):
    """Current-build canary: sampling mints replayable recordings,
    stamps recording_refs, and parity holds (the regression stream's
    steady state)."""
    from rca_tpu.gateway import run_canary
    from rca_tpu.replay import load_recording
    from rca_tpu.store import InvestigationStore

    store = InvestigationStore(root=str(tmp_path / "logs"))
    report = run_canary(
        str(tmp_path / "corpus"), rounds=1, ticks=6, services=12,
        seed=5, mode="both", store=store, serve_requests=4,
    )
    assert report["ok"], report
    assert report["sampled"] == 2      # one stream + one serve leg
    assert {r["mode"] for r in report["recordings"]} == {
        "stream", "serve",
    }
    listed = store.list_investigations()
    assert len(listed) == 2 and all(i["replayable"] for i in listed)
    ref = store.get_recording_ref(listed[0]["id"])
    assert ref and load_recording(ref).clean_close


def test_canary_sample_rate_zero_samples_nothing(tmp_path):
    from rca_tpu.gateway import run_canary

    report = run_canary(
        str(tmp_path / "corpus"), rounds=3, ticks=4, services=8,
        seed=0, sample_rate=0.0,
    )
    assert report["ok"]                # vacuously: nothing to replay
    assert report["sampled"] == 0 and report["skipped"] == 3


def test_canary_catches_perturbed_config_at_bisected_tick(tmp_path):
    """The acceptance gate: a deliberately perturbed scoring config
    diverges, the canary fails, and the tick it names IS the exact tick
    an independent bisect localizes."""
    from rca_tpu.gateway import build_candidate_engine, run_canary
    from rca_tpu.replay import bisect_divergence

    candidate, info = build_candidate_engine(decay=0.5)
    assert info["param_overrides"] == {"decay": 0.5}
    report = run_canary(
        str(tmp_path / "corpus"), rounds=1, ticks=8, services=12,
        seed=3, mode="stream", candidate=candidate,
        candidate_info=info,
    )
    assert not report["ok"]
    assert report["first_divergence"] is not None
    named = report["first_divergence"]["tick"]
    entry = report["recordings"][0]
    assert entry["parity_ok"] is False
    assert entry["first_divergent_tick"] == named
    assert os.path.exists(entry["dump"])
    # the exactness claim: an independent bisect of the same recording
    # against the same candidate names the same tick
    independent = bisect_divergence(
        entry["recording"], engine=candidate,
        dump_path=str(tmp_path / "dump.json"),
    )
    assert independent["divergent"]
    assert independent["first_divergent_tick"] == named


def test_canary_cli_exits_nonzero_on_divergence(tmp_path, capsys):
    """`rca canary` against a perturbed candidate exits nonzero and the
    report names the divergent tick (acceptance criterion)."""
    from rca_tpu.cli import main

    rc = main([
        "canary", "--out", str(tmp_path / "corpus"),
        "--rounds", "1", "--ticks", "6", "--fixture", "12svc",
        "--seed", "4", "--candidate-decay", "0.45",
        "--log-dir", str(tmp_path / "logs"), "--compact",
    ])
    out = capsys.readouterr().out
    assert rc == 1
    report = json.loads(out)
    assert not report["ok"]
    assert isinstance(report["first_divergence"]["tick"], int)
    # and the clean run exits 0, growing the same corpus dir
    rc = main([
        "canary", "--out", str(tmp_path / "corpus2"),
        "--rounds", "1", "--ticks", "6", "--fixture", "12svc",
        "--seed", "4", "--no-store", "--compact",
    ])
    assert rc == 0


# -- subprocess `rca serve --listen` (the acceptance gate) --------------------

def test_subprocess_listen_wire_parity(tmp_path, engine, case):
    """Spawn `rca serve --listen 127.0.0.1:0` as a real subprocess,
    drive a concurrency-16 loopback load, and assert bitwise ranking
    parity vs in-process analysis.  SIGTERM shuts it down cleanly."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RCA_SHARD"] = "off"   # dense engine: bitwise parity is
    #                            like-for-like vs the local GraphEngine
    proc = subprocess.Popen(
        [sys.executable, "-m", "rca_tpu", "serve",
         "--listen", "127.0.0.1:0", "--max-batch", "8",
         "--log-dir", str(tmp_path / "logs")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env, cwd=REPO_ROOT,
    )
    try:
        banner: list = []

        def read_banner() -> None:
            banner.append(proc.stdout.readline())

        reader = threading.Thread(target=read_banner)
        reader.start()
        reader.join(180.0)
        assert banner and banner[0], (
            f"no listen banner; stderr: {proc.stderr.read()[-2000:]}"
            if proc.poll() is not None else "gateway did not report in"
        )
        info = json.loads(banner[0])
        host, port = info["listening"].rsplit(":", 1)
        assert info["endpoints"] == [
            "/v1/analyze", "/v1/subscribe", "/v1/traces", "/metrics",
            "/healthz",
        ]
        cl = GatewayClient(host, int(port), timeout_s=120.0)
        rng = np.random.default_rng(2)
        feats = [
            np.clip(case.features + rng.uniform(
                0, 0.05, case.features.shape
            ).astype(np.float32), 0, 1)
            for _ in range(16)
        ]
        results: list = [None] * 16

        def worker(i: int) -> None:
            results[i] = cl.analyze(
                feats[i], case.dep_src, case.dep_dst,
                names=case.names, tenant=f"t{i % 4}", k=3,
            )

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, f in enumerate(feats):
            code, body, _ = results[i]
            assert code == 200, body
            solo = engine.analyze_arrays(
                f, case.dep_src, case.dep_dst, case.names, k=3
            )
            # bitwise ranking parity ACROSS THE PROCESS BOUNDARY
            assert body["ranked"] == solo.ranked
        code, health = cl.healthz()
        assert code == 200 and health["ok"]
    finally:
        proc.terminate()
        try:
            proc.wait(60.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(10.0)
    assert proc.returncode == 0


# -- TLS + bearer authn (ISSUE 15) -------------------------------------------

CERT = os.path.join(REPO_ROOT, "tests", "fixtures", "gateway_cert.pem")
KEY = os.path.join(REPO_ROOT, "tests", "fixtures", "gateway_key.pem")


def _unstarted_loop(engine, cap=2):
    """A saturatable, never-running plane: authn outcomes complete at
    (or before) admission, no device work."""
    return ServeLoop(engine=engine, config=ServeConfig(queue_cap=cap))


def test_gateway_tokens_parse_and_reject(monkeypatch):
    from rca_tpu.config import gateway_tokens, parse_gateway_tokens

    parsed = parse_gateway_tokens("tokA:acme,tokB:beta:1900000000")
    assert parsed == {"tokA": ("acme", None),
                      "tokB": ("beta", 1900000000.0)}
    for bad in ("lonetoken", "a:b,a:c", ":t", "tok:tenant:soon"):
        with pytest.raises(ValueError):
            parse_gateway_tokens(bad)
    monkeypatch.setenv("RCA_GATEWAY_TOKENS", "s3kr1t:solo")
    assert gateway_tokens() == {"s3kr1t": ("solo", None)}
    monkeypatch.delenv("RCA_GATEWAY_TOKENS")
    assert gateway_tokens() == {}


def test_gateway_tls_files_pair_enforced(monkeypatch):
    from rca_tpu.config import gateway_tls_files

    monkeypatch.delenv("RCA_GATEWAY_TLS_CERT", raising=False)
    monkeypatch.delenv("RCA_GATEWAY_TLS_KEY", raising=False)
    assert gateway_tls_files() is None
    monkeypatch.setenv("RCA_GATEWAY_TLS_CERT", CERT)
    with pytest.raises(ValueError):
        gateway_tls_files()          # half-configured TLS fails loudly
    monkeypatch.setenv("RCA_GATEWAY_TLS_KEY", KEY)
    assert gateway_tls_files() == (CERT, KEY)


def test_authn_rejects_before_body_and_queue(engine):
    """Missing/bad/expired token → 401, spoofed tenant → 403 — all
    BEFORE the serve queue: the saturable loop's queue stays EMPTY
    through every rejected request, and a huge declared body is never
    read."""
    loop = _unstarted_loop(engine)
    wall = [1000.0]
    gw = GatewayServer(
        loop, port=0,
        tokens={"tok-a": ("tenant-a", None),
                "tok-old": ("tenant-o", 999.0)},
        wall=lambda: wall[0],
    )
    gw.start()
    try:
        feats = np.zeros((4, 4), np.float32)
        # missing token
        code, body, _ = GatewayClient(gw.host, gw.port).analyze(
            feats, [0], [1]
        )
        assert code == 401 and "bearer" in body["detail"].lower()
        # bad token
        code, body, _ = GatewayClient(
            gw.host, gw.port, token="wrong"
        ).analyze(feats, [0], [1])
        assert code == 401
        # expired token (wall seam is past the token's expiry)
        code, body, _ = GatewayClient(
            gw.host, gw.port, token="tok-old"
        ).analyze(feats, [0], [1])
        assert code == 401 and "expired" in body["detail"]
        # spoofed tenant header on a valid token
        code, body, _ = GatewayClient(
            gw.host, gw.port, token="tok-a"
        ).analyze(feats, [0], [1], tenant="tenant-b")
        assert code == 403
        # none of the rejects touched the queue or read the body
        assert len(loop.queue) == 0
        assert gw.metrics.snapshot()["auth_rejections"] == 4
        # 401 happens even with a huge DECLARED body: headers only
        conn = http.client.HTTPConnection(gw.host, gw.port, timeout=10)
        try:
            conn.putrequest("POST", "/v1/analyze")
            conn.putheader("Content-Length", str(1 << 30))
            conn.endheaders()
            resp = conn.getresponse()
            assert resp.status == 401
            resp.read()
        finally:
            conn.close()
        # GET surfaces are gated too; /healthz stays open for probes
        code, _, _hdrs = _raw_get(gw, "/metrics")
        assert code == 401
        code, _, _hdrs = _raw_get(gw, "/healthz")
        assert code in (200, 503)
    finally:
        gw.close()


def _raw_get(gw, path, headers=None):
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=10)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def test_token_binds_tenant_end_to_end(engine, case):
    """A valid token serves — and the response tenant is the TOKEN's,
    whatever the body claimed (the header spoof already 403s; the body
    tenant is silently overridden, same precedence as the header)."""
    loop = ServeLoop(engine=engine).start()
    try:
        gw = GatewayServer(loop, port=0,
                           tokens={"tok-a": ("tenant-a", None)})
        gw.start()
        try:
            cl = GatewayClient(gw.host, gw.port, token="tok-a")
            code, body, _ = cl.analyze(
                case.features, case.dep_src, case.dep_dst,
                names=case.names, k=3,
            )
            assert code == 200 and body["status"] == "ok"
            assert body["tenant"] == "tenant-a"
            # matching header is fine (not a spoof)
            code, body, _ = cl.analyze(
                case.features, case.dep_src, case.dep_dst,
                names=case.names, k=3, tenant="tenant-a",
            )
            assert code == 200
        finally:
            gw.close()
    finally:
        loop.stop()


def test_tls_handshake_and_plaintext_rejection(engine, case):
    """TLS gateway: a verified HTTPS client round-trips bit-identical
    rankings; a plaintext client dies at the handshake — rejected
    before the serve queue by construction."""
    loop = ServeLoop(engine=engine).start()
    try:
        gw = GatewayServer(loop, port=0, tls=(CERT, KEY))
        gw.start()
        try:
            cl = GatewayClient(gw.host, gw.port, tls=True, ca_file=CERT)
            code, body, _ = cl.analyze(
                case.features, case.dep_src, case.dep_dst,
                names=case.names, k=3,
            )
            assert code == 200 and body["status"] == "ok"
            solo = engine.analyze_arrays(
                case.features, case.dep_src, case.dep_dst, case.names,
                k=3,
            )
            assert body["ranked"] == solo.ranked   # parity through TLS
            # plaintext to the TLS port: dead at the handshake
            with pytest.raises((OSError, http.client.HTTPException)):
                conn = http.client.HTTPConnection(
                    gw.host, gw.port, timeout=5
                )
                try:
                    conn.request("GET", "/healthz")
                    conn.getresponse().read()
                finally:
                    conn.close()
            # unverified-but-encrypted client (no ca_file) also works —
            # the caller had to ask for no-verify by name
            code, _ = GatewayClient(
                gw.host, gw.port, tls=True
            ).healthz()
            assert code == 200
        finally:
            gw.close()
    finally:
        loop.stop()


def test_tls_authn_stack_over_federation_plane(engine, case):
    """The ISSUE 15 front-door acceptance shape: TLS + tokens over a
    FEDERATION plane (fake in-process worker speaking the real wire
    protocol) — https analyze round-trips; plaintext and token-less
    requests never reach the plane's queue."""
    from rca_tpu.serve.federation import FederationPlane
    from rca_tpu.serve.fedwire import FrameConn, PROTO
    from rca_tpu.util.net import make_client_socket
    from rca_tpu.util.threads import spawn

    plane = FederationPlane(workers=1, spawn_workers=False,
                            heartbeat_s=0.05)
    plane.start()

    def fake_worker():
        sock = make_client_socket("fed-test", plane.host, plane.port)
        conn = FrameConn(sock, "fed-test")
        conn.send({"t": "hello", "proto": PROTO, "worker_id": 0,
                   "pid": 0, "engine": "fake"})
        lease = [None]

        def hb():
            import time as _t
            seq = 0
            while not conn.closed:
                _t.sleep(0.05)
                if lease[0]:
                    seq += 1
                    if not conn.send({"t": "hb", "worker_id": 0,
                                      "lease_id": lease[0],
                                      "seq": seq}):
                        return
        spawn(hb, name="fed-test-hb", daemon=True)
        while True:
            msg = conn.recv()
            if msg is None:
                return
            if msg["t"] == "lease":
                lease[0] = msg["lease_id"]
            elif msg["t"] == "req":
                conn.send({
                    "t": "resp", "request_id": msg["request_id"],
                    "status": "ok",
                    "ranked": [{"component": "svc-0", "score": 1.0}],
                    "batch_size": 1, "engine": "fake",
                })
            elif msg["t"] == "drain":
                conn.send({"t": "drained"})
                return

    spawn(fake_worker, name="fed-test-worker", daemon=True)
    assert plane.wait_ready(1, timeout_s=10.0)
    try:
        gw = GatewayServer(plane, port=0, tls=(CERT, KEY),
                           tokens={"fed-tok": ("fed-tenant", None)})
        gw.start()
        try:
            cl = GatewayClient(gw.host, gw.port, tls=True,
                               ca_file=CERT, token="fed-tok")
            code, body, _ = cl.analyze(
                case.features, case.dep_src, case.dep_dst, k=3,
            )
            assert code == 200 and body["tenant"] == "fed-tenant"
            # /healthz reads the plane's lease-fed health
            code, health = cl.healthz()
            assert code == 200 and health["ok"]
            assert health["workers"] == {"0": "live"}
            # token-less HTTPS request: 401 before the plane's queue
            code, body, _ = GatewayClient(
                gw.host, gw.port, tls=True
            ).analyze(case.features, case.dep_src, case.dep_dst)
            assert code == 401
            assert len(plane.queue) == 0
        finally:
            gw.close()
    finally:
        plane.stop()


# -- Retry-After jitter + client retries (ISSUE 15 small fix) ----------------


def test_retry_after_jitter_breaks_thundering_herd(engine):
    """Six consecutive 429s carry DISTINCT jittered ms hints (seeded —
    deterministic per gateway), while the integer Retry-After stays a
    spec-shaped ceiling of the hint."""
    loop = _unstarted_loop(engine)
    for i in range(2):
        assert loop.submit(_req(seed=i))     # saturate
    with GatewayServer(loop, port=0, retry_jitter_seed=7) as gw:
        cl = GatewayClient(gw.host, gw.port)
        hints = []
        for _ in range(6):
            code, _body, headers = cl.analyze(
                np.zeros((4, 4), np.float32), [0], [1]
            )
            assert code == 429
            ms = int(headers["X-RCA-Retry-After-Ms"])
            secs = int(headers["Retry-After"])
            assert 1000 <= ms < 3001
            assert secs >= ms / 1000.0       # ceiling, never earlier
            hints.append(ms)
        assert len(set(hints)) >= 5          # de-synchronized retries


def test_client_retries_honor_jittered_hint(engine):
    """GatewayClient sleeps the SERVER's jittered hint between retries
    and lands the request once capacity returns."""
    loop = _unstarted_loop(engine, cap=1)
    assert loop.submit(_req(seed=0))         # saturate cap=1
    sleeps: list = []
    # the gateway's own wait bound is tight so the RETRIED (admitted,
    # never served — the loop doesn't run) request answers 504 fast
    with GatewayServer(loop, port=0, retry_jitter_seed=3,
                       timeout_s=1.0) as gw:
        def sleeper(s: float) -> None:
            sleeps.append(s)
            # free the queue on the first backoff: the retry must land
            if len(sleeps) == 1:
                loop.queue.pop()

        cl = GatewayClient(gw.host, gw.port, sleeper=sleeper)
        code, body, _ = cl.analyze(
            np.zeros((4, 4), np.float32), [0], [1], retries=3,
        )
        # exactly one backoff (the jittered hint), then ADMITTED —
        # proven by the queue depth; the 504 is the gateway's honest
        # bound on the never-running stub loop
        assert len(sleeps) == 1
        assert 1.0 <= sleeps[0] <= 3.001     # the jittered hint
        assert code == 504
        assert len(loop.queue) == 1
    loop.queue.pop()


def test_retry_delay_prefers_ms_header():
    assert GatewayClient.retry_delay_s(
        {"X-RCA-Retry-After-Ms": "1750", "Retry-After": "2"}
    ) == 1.75
    assert GatewayClient.retry_delay_s({"Retry-After": "3"}) == 3.0
    assert GatewayClient.retry_delay_s({}) == 1.0


# -- canary off a live gateway (ISSUE 15 satellite) --------------------------


def test_canary_samples_through_live_gateway(engine, tmp_path):
    """`rca canary --listen-url`: sampling goes over the WIRE of a
    running (token-authed) gateway; the minted recording replays with
    bit parity against the current build — the federation path now
    mints regression corpora too."""
    from rca_tpu.gateway.canary import run_canary

    loop = ServeLoop(engine=engine).start()
    try:
        gw = GatewayServer(loop, port=0,
                           tokens={"can-tok": ("canary", None)})
        gw.start()
        try:
            report = run_canary(
                str(tmp_path / "corpus"),
                rounds=2, services=20, seed=0, serve_requests=3, k=3,
                listen_url=f"http://{gw.host}:{gw.port}",
                token="can-tok",
            )
            assert report["ok"], report
            assert report["mode"] == "gateway"
            assert report["sampled"] == 2
            for rec in report["recordings"]:
                assert rec["parity_ok"]
                assert rec["mode"] == "serve"
        finally:
            gw.close()
    finally:
        loop.stop()


# -- mTLS: client-certificate front door (ISSUE 16) --------------------------

CLIENT_CERT = os.path.join(
    REPO_ROOT, "tests", "fixtures", "client_cert.pem"
)
CLIENT_KEY = os.path.join(
    REPO_ROOT, "tests", "fixtures", "client_key.pem"
)


def test_gateway_tls_client_ca_requires_pair(monkeypatch, engine):
    from rca_tpu.config import gateway_tls_client_ca

    monkeypatch.delenv("RCA_GATEWAY_TLS_CERT", raising=False)
    monkeypatch.delenv("RCA_GATEWAY_TLS_KEY", raising=False)
    monkeypatch.delenv("RCA_GATEWAY_TLS_CLIENT_CA", raising=False)
    assert gateway_tls_client_ca() is None
    # client-CA without a TLS listener: an mTLS knob on a plaintext
    # port would silently verify nobody — fail loudly instead
    monkeypatch.setenv("RCA_GATEWAY_TLS_CLIENT_CA", CLIENT_CERT)
    with pytest.raises(ValueError):
        gateway_tls_client_ca()
    monkeypatch.setenv("RCA_GATEWAY_TLS_CERT", CERT)
    monkeypatch.setenv("RCA_GATEWAY_TLS_KEY", KEY)
    assert gateway_tls_client_ca() == CLIENT_CERT
    # same contract on the constructor path (env cleared: nothing to
    # fall back to, so a client CA alone must refuse to build)
    monkeypatch.delenv("RCA_GATEWAY_TLS_CERT")
    monkeypatch.delenv("RCA_GATEWAY_TLS_KEY")
    monkeypatch.delenv("RCA_GATEWAY_TLS_CLIENT_CA")
    loop = _unstarted_loop(engine)
    with pytest.raises(ValueError):
        GatewayServer(loop, port=0, tls=None, tls_client_ca=CLIENT_CERT)


def test_mtls_client_cert_enforced(engine, case):
    """Mutual TLS: a client presenting the pinned fixture cert
    round-trips; a cert-less (or wrong-cert) client dies at the
    handshake and is COUNTED in auth_rejections — refused credentials
    look the same in the metrics whatever layer refused them."""
    loop = ServeLoop(engine=engine).start()
    try:
        # the self-signed client cert is its own CA: pin exactly it
        gw = GatewayServer(loop, port=0, tls=(CERT, KEY),
                           tls_client_ca=CLIENT_CERT)
        gw.start()
        try:
            cl = GatewayClient(
                gw.host, gw.port, tls=True, ca_file=CERT,
                cert_file=CLIENT_CERT, key_file=CLIENT_KEY,
            )
            code, health = cl.healthz()
            assert code == 200 and health["ok"]
            code, body, _ = cl.analyze(
                case.features, case.dep_src, case.dep_dst, k=3,
            )
            assert code == 200 and body["status"] == "ok"
            # no client cert: dead at the handshake, before any route
            with pytest.raises((OSError, http.client.HTTPException)):
                GatewayClient(
                    gw.host, gw.port, tls=True, ca_file=CERT
                ).healthz()
            # a cert the pinned CA did not sign is equally dead
            with pytest.raises((OSError, http.client.HTTPException)):
                GatewayClient(
                    gw.host, gw.port, tls=True, ca_file=CERT,
                    cert_file=CERT, key_file=KEY,
                ).healthz()
            assert gw.metrics.snapshot()["auth_rejections"] >= 2
        finally:
            gw.close()
    finally:
        loop.stop()
