"""Investigation store, evidence logger, prompt logger."""

import json
import multiprocessing

import pytest

from rca_tpu.obslog import EvidenceLogger, PromptLogger
from rca_tpu.store import ACCUMULATED_FINDINGS_CAP, InvestigationStore


@pytest.fixture
def store(tmp_path):
    return InvestigationStore(root=str(tmp_path / "logs"))


def test_investigation_lifecycle(store):
    inv = store.create_investigation("DB down", namespace="prod")
    iid = inv["id"]
    assert inv["status"] == "active"
    assert set(inv) >= {
        "id", "title", "namespace", "context", "created_at", "updated_at",
        "summary", "status", "conversation", "evidence", "agent_findings",
        "next_actions", "accumulated_findings",
    }

    store.add_message(iid, "user", "why is the db down?")
    store.add_message(iid, "assistant", {"summary": "crash loop"})
    store.set_next_actions(iid, [{"text": "check logs", "priority": "high"}])
    store.add_evidence(iid, "pod_status", {"phase": "Running"})
    store.add_agent_findings(iid, "logs", [{"issue": "oom"}])
    store.update_summary(iid, "database crash looping")
    store.save_hypothesis(iid, {"description": "bad init script"})

    got = store.get_investigation(iid)
    assert len(got["conversation"]) == 2
    assert got["conversation"][0]["role"] == "user"
    assert got["next_actions"][0]["priority"] == "high"
    assert got["evidence"]["pod_status"]["phase"] == "Running"
    assert got["agent_findings"]["logs"][0]["issue"] == "oom"
    assert got["summary"] == "database crash looping"
    assert got["hypotheses"][0]["description"] == "bad init script"


def test_accumulated_findings_cap_and_dedup(store):
    inv = store.create_investigation("t")
    iid = inv["id"]
    store.add_accumulated_findings(iid, ["a", "b", "a"])
    got = store.get_investigation(iid)
    assert got["accumulated_findings"] == ["a", "b"]
    store.add_accumulated_findings(
        iid, [f"f{i}" for i in range(ACCUMULATED_FINDINGS_CAP + 5)]
    )
    got = store.get_investigation(iid)
    assert len(got["accumulated_findings"]) == ACCUMULATED_FINDINGS_CAP
    assert got["accumulated_findings"][-1] == f"f{ACCUMULATED_FINDINGS_CAP + 4}"


def test_list_sorted_newest_first(store):
    a = store.create_investigation("first")
    b = store.create_investigation("second")
    store.add_message(a["id"], "user", "bump")  # a updated most recently
    lst = store.list_investigations()
    assert [r["title"] for r in lst] == ["first", "second"]
    assert lst[0]["messages"] == 1


def test_missing_investigation_returns_none(store):
    assert store.get_investigation("nope") is None
    assert store.add_message("nope", "user", "x") is None


def _writer(args):
    root, iid, start = args
    store = InvestigationStore(root=root)
    for i in range(start, start + 20):
        store.add_message(iid, "user", f"m{i}")
    return True


def test_concurrent_writers_do_not_lose_messages(store):
    """The reference had no locking (SURVEY.md §5); here 3 processes
    appending concurrently must lose nothing."""
    inv = store.create_investigation("race")
    iid = inv["id"]
    with multiprocessing.Pool(3) as pool:
        pool.map(_writer, [(str(store.root), iid, k * 100) for k in range(3)])
    got = store.get_investigation(iid)
    assert len(got["conversation"]) == 60


def _serve_stub_parts():
    """Device-free serve loop parts for the store-contention tests."""

    class _Handle:
        def __init__(self, requests, at):
            self.requests, self.dispatched_at = requests, at

    class _Result:
        ranked = [{"component": "svc-0", "score": 1.0}]
        engine = "stub"

    class _Stub:
        engine = None

        def dispatch(self, batch, now=None):
            return _Handle(list(batch), now if now is not None else 0.0)

        def fetch(self, handle):
            return [_Result() for _ in handle.requests]

    import numpy as np

    feats = np.ones((8, 4), np.float32)
    src = np.arange(7, dtype=np.int32)
    dst = np.arange(1, 8, dtype=np.int32)
    return _Stub(), feats, src, dst


def test_serve_path_concurrent_appends_no_lost_updates(store):
    """ISSUE 3 satellite: N threads appending to ONE investigation
    through the serve path — submitter threads write user messages while
    the serve worker appends its per-request serve notes to the same
    file.  The store's fcntl locking must lose nothing."""
    import threading

    from rca_tpu.config import ServeConfig
    from rca_tpu.serve import ServeLoop, ServeRequest

    stub, feats, src, dst = _serve_stub_parts()
    inv = store.create_investigation("serve stress")
    iid = inv["id"]
    loop = ServeLoop(
        config=ServeConfig(max_batch=4, max_wait_us=0, queue_cap=256),
        dispatcher=stub, store=store,
    ).start()
    n, workers = 32, 8
    reqs = [None] * n

    def submitter(w):
        for i in range(w, n, workers):
            store.add_message(iid, "user", f"query-{i}")
            reqs[i] = ServeRequest(
                tenant=f"t{w}", features=feats, dep_src=src, dep_dst=dst,
                investigation_id=iid,
            )
            loop.submit(reqs[i])

    threads = [
        threading.Thread(target=submitter, args=(w,)) for w in range(workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    resps = [r.result(60.0) for r in reqs]
    loop.stop()
    assert all(r.status == "ok" for r in resps)
    got = store.get_investigation(iid)
    roles = [m["role"] for m in got["conversation"]]
    assert roles.count("user") == n       # no lost submitter appends
    assert roles.count("serve") == n      # no lost worker appends


def test_lock_released_when_writer_crashes_mid_update(store):
    """A worker crashing INSIDE the locked read-modify-write section must
    release the fcntl lock (the context manager's finally), so the next
    writer proceeds instead of deadlocking."""
    import threading

    inv = store.create_investigation("crash")
    iid = inv["id"]

    def crasher():
        def mutate(_inv):
            raise RuntimeError("worker crash mid-update")

        with pytest.raises(RuntimeError):
            store._update(iid, mutate)

    t = threading.Thread(target=crasher)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    done = []
    t2 = threading.Thread(
        target=lambda: done.append(store.add_message(iid, "user", "after"))
    )
    t2.start()
    t2.join(timeout=10)
    assert done and done[0] is not None   # lock was released, not leaked
    got = store.get_investigation(iid)
    assert [m["content"] for m in got["conversation"]] == ["after"]


def test_serve_store_note_failure_does_not_fail_response(store):
    """A store failure on the serve worker's note append is suppressed
    (bounded fault log) — the request is still answered ok."""
    from rca_tpu.config import ServeConfig
    from rca_tpu.serve import ServeLoop, ServeRequest

    stub, feats, src, dst = _serve_stub_parts()

    class _BrokenStore:
        def add_message(self, *a, **kw):
            raise OSError("disk full")

    loop = ServeLoop(
        config=ServeConfig(max_wait_us=0),
        dispatcher=stub, store=_BrokenStore(),
    ).start()
    req = ServeRequest(
        tenant="t", features=feats, dep_src=src, dep_dst=dst,
        investigation_id="whatever",
    )
    loop.submit(req)
    resp = req.result(30.0)
    loop.stop()
    assert resp.status == "ok"


def test_evidence_logger_roundtrip(tmp_path):
    ev = EvidenceLogger(root=str(tmp_path / "ev"))
    p1 = ev.log_hypothesis(
        "inv1", "Pod/db", {"description": "liveness probe failing"},
        evidence={"restarts": 5},
    )
    ev.log_investigation_step(
        "inv1", "Pod/db", {"description": "check logs"}, result="logs ok",
        verdict={"verdict": "refuted", "confidence": 0.8},
    )
    ev.log_conclusion("inv1", "Pod/db", {"root_cause": "bad probe"})
    assert p1.name.endswith("_hypothesis.json")
    rec = json.loads(p1.read_text())
    assert rec["investigation_id"] == "inv1"
    hits = ev.get_evidence_for_hypothesis("liveness probe")
    assert len(hits) == 1
    assert ev.get_evidence_for_hypothesis("unrelated") == []


def test_prompt_logger_jsonl_format(tmp_path):
    pl = PromptLogger(root=str(tmp_path / "prompts"))
    pl.log_interaction(
        "the prompt", "the response",
        investigation_id="inv9", user_query="why?", namespace="prod",
        accumulated_findings=["f1"],
        additional_context={"provider": "offline", "model": "m",
                            "temperature": 0.2},
    )
    pl.log_system_event("provider_failover", {"from": "openai"})
    records = pl.read_all()
    assert len(records) == 2
    r = records[0]
    assert set(r) == {
        "timestamp", "investigation_id", "user_query", "prompt", "response",
        "namespace", "accumulated_findings", "additional_context",
    }
    assert r["additional_context"]["provider"] == "offline"
    assert records[1]["additional_context"]["system_event"] == "provider_failover"


def test_prompt_logger_llm_adapter(tmp_path):
    from rca_tpu.llm import LLMClient, OfflineProvider

    pl = PromptLogger(root=str(tmp_path / "prompts"))
    llm = LLMClient(
        provider=OfflineProvider(),
        log_fn=pl.as_log_fn(investigation_id="inv1", namespace="ns"),
    )
    llm.generate_completion("hello")
    records = pl.read_all()
    assert records[0]["investigation_id"] == "inv1"
    assert records[0]["additional_context"]["provider"] == "offline"


def test_recorded_investigation_fixture_resumes(tmp_path):
    """Schema-stability oracle (reference kept logs/*.json as regression
    fixtures, SURVEY.md §4 layer 4): a recorded investigation from an
    earlier build must load in a fresh store and RESUME — list, render,
    and continue with its accumulated findings feeding the next turn.  If
    a schema change orphans old investigations, this is the test that
    goes red."""
    import os
    import shutil

    from rca_tpu.cluster.fixtures import five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.coordinator import RCACoordinator

    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "recorded_investigation.json"
    )
    root = tmp_path / "logs"
    root.mkdir()
    shutil.copy(fixture, root / "rec-0001-fixture.json")

    store = InvestigationStore(root=str(root))
    rows = store.list_investigations()
    assert [r["id"] for r in rows] == ["rec-0001-fixture"]
    inv = store.get_investigation("rec-0001-fixture")
    # full recorded surface is intact
    assert inv["title"] == "Database crash loop"
    assert inv["namespace"] == "test-microservices"
    assert len(inv["conversation"]) == 2
    assert inv["conversation"][1]["content"]["response_data"]["points"]
    assert inv["next_actions"] and inv["accumulated_findings"]
    top = inv["agent_findings"]["comprehensive"]["root_causes"][0]
    assert top["component"] == "database"

    # resume: a follow-up turn consumes the recorded accumulated findings
    coord = RCACoordinator(
        MockClusterClient(five_service_world()), backend="deterministic"
    )
    out = coord.process_user_query(
        "what should I fix first?", inv["namespace"],
        previous_findings=inv["accumulated_findings"],
    )
    store.add_message("rec-0001-fixture", "user", "what should I fix first?")
    store.add_message("rec-0001-fixture", "assistant",
                      {"response_data": out["response_data"]})
    resumed = store.get_investigation("rec-0001-fixture")
    assert len(resumed["conversation"]) == 4


def test_delete_and_update_status(store):
    inv = store.create_investigation("temp", namespace="x")
    iid = inv["id"]
    store.update_status(iid, "resolved")
    assert store.get_investigation(iid)["status"] == "resolved"
    assert store.delete_investigation(iid) is True
    assert store.get_investigation(iid) is None
    assert store.delete_investigation(iid) is False  # already gone
