"""elasticmesh: the autoscaling worker fleet (ISSUE 16).

Four layers, cheapest first:

- pure units: SCALE_RULES / PLACEMENT_RULES table validation (a typo'd
  rule must fail at construction), shape-tier lookup, and the hello
  placement-evidence parsers;
- the CONTROLLER on a fake clock against a stub plane: hysteresis
  (sustain windows), cooldown, min/max clamps, least-loaded victim
  selection, force semantics, and the breach re-arm after an action;
- the CONTROL PLANE against in-process fake workers speaking the real
  wire protocol: shape-aware placement preferring advertised winning
  timings (headroom tie-breaks), rendezvous fallback, drain-based
  scale-down retiring a worker as ``worker_scaled_down`` (never
  ``process_kill``), and worker-id monotonicity;
- the THREADED 2→8→2 load-ramp soak (real WorkerAgents + ServeLoops
  over real sockets): all-terminal + exactly-once + bounded windowed
  p99 through both transitions — the acceptance gate, run in-tree.

The worker's seeded rejoin backoff (the ISSUE 16 small fix) is
regression-tested at the wire level: a coordinator-side script of
stale-lease rejects must observe DISTINCT, seeded sleep delays.
"""

from __future__ import annotations

import collections
import time

import numpy as np
import pytest

from rca_tpu.serve.autoscale import (
    PLACEMENT_RULES,
    SCALE_RULES,
    SCALING_FAULT_CLASSES,
    AutoscaleController,
    PlacementRule,
    PlacementRuleSet,
    ScaleRule,
    ScaleRuleSet,
    run_scale_ramp_soak,
    run_scaling_storm,
    shape_tier_ms,
)
from rca_tpu.serve.federation import (
    FederationPlane,
    _parse_headroom,
    _parse_shape_summary,
)
from rca_tpu.serve.fedwire import FrameConn, FrameError, PROTO
from rca_tpu.serve.request import ServeRequest
from rca_tpu.util.net import make_client_socket
from rca_tpu.util.threads import make_lock, spawn


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _req(tenant="t", n=8, seed=0, **kw) -> ServeRequest:
    rng = np.random.default_rng(seed)
    feats = rng.random((n, 14), dtype=np.float32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    return ServeRequest(
        tenant=tenant, features=feats, dep_src=src, dep_dst=dst, **kw
    )


# ---------------------------------------------------------------------------
# Rule-table validation (loud at construction)
# ---------------------------------------------------------------------------


def test_default_tables_are_valid():
    assert len(SCALE_RULES.rules) >= 2
    assert any(r.action == "up" for r in SCALE_RULES.rules)
    assert any(r.action == "down" for r in SCALE_RULES.rules)
    assert PLACEMENT_RULES.rules[-1].min_services == 0
    assert SCALING_FAULT_CLASSES == ("scaling_storm",)


def _rule(**kw) -> ScaleRule:
    base = dict(name="r", signal="queue_depth", op=">", threshold=1.0,
                for_s=1.0, action="up", step=1)
    base.update(kw)
    return ScaleRule(**base)


@pytest.mark.parametrize("bad", [
    (),                                              # empty
    (_rule(), _rule(action="down", op="<")),         # duplicate names
    (_rule(signal="nope"), _rule(name="d", action="down", op="<")),
    (_rule(op=">="), _rule(name="d", action="down", op="<")),
    (_rule(action="sideways"), _rule(name="d", action="down", op="<")),
    (_rule(threshold=-1.0), _rule(name="d", action="down", op="<")),
    (_rule(for_s=-0.1), _rule(name="d", action="down", op="<")),
    (_rule(step=0), _rule(name="d", action="down", op="<")),
    (_rule(),),                                      # no down rule
    (_rule(action="down", op="<"),),                 # no up rule
])
def test_scale_ruleset_rejects_malformed(bad):
    with pytest.raises(ValueError):
        ScaleRuleSet(rules=bad)


def test_scale_ruleset_requires_hysteresis_band():
    """One signal driving both directions must leave a dead zone, or a
    steady value fires up and down alternately — the exact flap the
    table exists to prevent."""
    with pytest.raises(ValueError, match="hysteresis"):
        ScaleRuleSet(rules=(
            _rule(name="up", signal="occupancy", op=">", threshold=0.5),
            _rule(name="down", signal="occupancy", op="<", threshold=0.5,
                  action="down"),
        ))
    # a proper band is fine
    ScaleRuleSet(rules=(
        _rule(name="up", signal="occupancy", op=">", threshold=0.8),
        _rule(name="down", signal="occupancy", op="<", threshold=0.2,
              action="down"),
    ))


@pytest.mark.parametrize("bad", [
    (),                                              # empty
    (PlacementRule("a", 10), PlacementRule("a", 0)),  # dup names
    (PlacementRule("a", 10, ("vibes",)), PlacementRule("b", 0)),
    (PlacementRule("a", 10), PlacementRule("b", 10)),  # not descending
    (PlacementRule("a", 10),),                       # last not 0
])
def test_placement_ruleset_rejects_malformed(bad):
    with pytest.raises(ValueError):
        PlacementRuleSet(rules=bad)


def test_placement_rule_for_first_match_descending():
    rs = PlacementRuleSet(rules=(
        PlacementRule("big", 100, ("timings", "headroom")),
        PlacementRule("mid", 10, ("timings",)),
        PlacementRule("small", 0),
    ))
    assert rs.rule_for(500).name == "big"
    assert rs.rule_for(100).name == "big"
    assert rs.rule_for(99).name == "mid"
    assert rs.rule_for(3).name == "small"


def test_shape_tier_ms_covering_then_largest():
    shapes = {64: 1.5, 256: 9.0}
    assert shape_tier_ms(shapes, 48) == 1.5     # smallest covering pad
    assert shape_tier_ms(shapes, 64) == 1.5
    assert shape_tier_ms(shapes, 100) == 9.0
    assert shape_tier_ms(shapes, 4096) == 9.0   # undersized: largest
    assert shape_tier_ms({}, 48) is None


def test_hello_evidence_parsers_drop_malformed():
    assert _parse_shape_summary(
        {"64": 1.5, "256": "9.0", "bad": 2.0, "-3": 1.0, "0": 1.0,
         "32": -1.0}
    ) == {64: 1.5, 256: 9.0}
    assert _parse_shape_summary(None) == {}
    assert _parse_shape_summary("garbage") == {}
    assert _parse_headroom({"bytes_in_use": 1024}) == 1024
    assert _parse_headroom({"bytes_in_use": "1024"}) == 1024
    assert _parse_headroom({"bytes_in_use": "lots"}) is None
    assert _parse_headroom(None) is None
    assert _parse_headroom({}) is None


# ---------------------------------------------------------------------------
# Controller on a fake clock (stub plane — pure policy)
# ---------------------------------------------------------------------------


class StubMetrics:
    def __init__(self):
        self.events = collections.Counter()
        self.signals = {
            "queue_ms_p99_recent": None,
            "recent_samples": 0,
            "slo_breach_total": 0,
        }
        self.placements = collections.Counter()

    def autoscale_signals(self):
        return dict(self.signals)

    def scale_event(self, kind):
        self.events[kind] += 1

    def placement(self, outcome):
        self.placements[outcome] += 1


class StubPlane:
    """Just enough surface for the controller: live-set arithmetic,
    spawn/drain recording, and the metrics signal block."""

    def __init__(self, clock, live=2, window=4):
        self.clock = clock
        self.metrics = StubMetrics()
        self.queue = []
        self.window = window
        self.autoscaler = None
        self.workers = {i: 0 for i in range(live)}   # wid -> outstanding
        self.spawned = []
        self.drained = []
        self.events = []

    def scale_status(self):
        live = sorted(self.workers)
        return {
            "live": live, "draining": [],
            "outstanding": dict(self.workers),
            "next_id": (max(self.workers) + 1) if self.workers else 0,
        }

    def pending_count(self):
        return sum(self.workers.values())

    def spawn_worker(self, wid):
        self.workers[wid] = 0
        self.spawned.append(wid)

    def drain_worker(self, wid):
        if wid not in self.workers:
            return False
        del self.workers[wid]
        self.drained.append(wid)
        return True

    def _event(self, name, wid, **kw):
        self.events.append({"event": name, "worker_id": wid, **kw})


def _controller(plane, **kw):
    kw.setdefault("min_workers", 1)
    kw.setdefault("max_workers", 8)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("interval_s", 1.0)
    return AutoscaleController(plane, **kw)


def test_bounds_validation():
    plane = StubPlane(FakeClock())
    with pytest.raises(ValueError):
        AutoscaleController(plane, min_workers=0, max_workers=4)
    with pytest.raises(ValueError):
        AutoscaleController(plane, min_workers=5, max_workers=4)


def test_sustain_window_gates_the_fire():
    """A breach must hold continuously for ``for_s`` before the rule
    fires — one spike never scales (hysteresis half 1)."""
    clock = FakeClock()
    plane = StubPlane(clock, live=2)
    ctl = _controller(plane)             # default SCALE_RULES
    plane.queue = [0] * 40               # queue_depth 40 > 32 (surge)
    d = ctl.run_once(now=1000.0)
    assert d["action"] == "hold"         # breach just started
    assert plane.spawned == []
    d = ctl.run_once(now=1004.0)         # 4s < for_s=5
    assert d["action"] == "hold"
    d = ctl.run_once(now=1005.0)         # sustained
    assert d["action"] == "up" and d["rule"] == "surge-depth"
    assert plane.spawned == [2, 3]       # step 2, ids continue from 2
    assert plane.metrics.events["scale_ups"] == 1


def test_breach_interruption_resets_sustain():
    clock = FakeClock()
    plane = StubPlane(clock, live=2)
    ctl = _controller(plane)
    plane.queue = [0] * 40
    ctl.run_once(now=1000.0)
    plane.queue = []                     # breach clears...
    ctl.run_once(now=1003.0)
    plane.queue = [0] * 40               # ...and returns: timer restarts
    ctl.run_once(now=1004.0)
    d = ctl.run_once(now=1008.0)         # only 4s since the RE-breach
    assert d["action"] == "hold"
    assert plane.spawned == []


def test_cooldown_blocks_consecutive_actions():
    clock = FakeClock()
    plane = StubPlane(clock, live=2)
    ctl = _controller(plane, cooldown_s=10.0)
    plane.queue = [0] * 40
    ctl.run_once(now=1000.0)
    assert ctl.run_once(now=1005.0)["action"] == "up"
    # still breaching, sustained again — but inside the cooldown
    ctl.run_once(now=1006.0)
    d = ctl.run_once(now=1012.0)
    assert d["action"] == "cooldown"
    assert plane.metrics.events["cooldown_skips"] >= 1
    assert plane.spawned == [2, 3]       # nothing further spawned
    # past the cooldown the same sustained breach acts again
    d = ctl.run_once(now=1016.0)
    assert d["action"] == "up"
    assert plane.spawned == [2, 3, 4, 5]


def test_max_clamp_holds_the_ceiling():
    clock = FakeClock()
    plane = StubPlane(clock, live=4)
    ctl = _controller(plane, max_workers=4)
    plane.queue = [0] * 40
    ctl.run_once(now=1000.0)
    d = ctl.run_once(now=1005.0)
    assert d["action"] == "clamped"
    assert plane.spawned == []
    assert plane.metrics.events["clamps"] == 1


def test_min_clamp_holds_the_floor():
    clock = FakeClock()
    plane = StubPlane(clock, live=2)
    ctl = _controller(plane, min_workers=2)
    # occupancy 0 < 0.10 — the idle-occupancy down rule (for_s 30)
    ctl.run_once(now=1000.0)
    d = ctl.run_once(now=1030.0)
    assert d["action"] == "clamped"
    assert plane.drained == []


def test_scale_down_picks_least_loaded_newest_first():
    clock = FakeClock()
    plane = StubPlane(clock, live=3)
    plane.workers = {0: 5, 1: 0, 2: 0}
    ctl = _controller(plane, min_workers=1)
    ctl.run_once(now=1000.0)             # occupancy 5/12 is not < 0.10?
    # occupancy = pending / (live * window) = 5/12 ≈ 0.42 — no breach;
    # empty the fleet so the idle rule breaches
    plane.workers = {0: 0, 1: 0, 2: 0}
    ctl.run_once(now=1001.0)
    d = ctl.run_once(now=1031.0)
    assert d["action"] == "down"
    # tie on outstanding → NEWEST id drains first (hot residency stays)
    assert plane.drained == [2]
    assert plane.metrics.events["scale_downs"] == 1


def test_action_rearms_every_sustain_window():
    """After any action the breach history is cleared: the fleet just
    changed, old evidence describes a dead topology."""
    clock = FakeClock()
    plane = StubPlane(clock, live=2)
    ctl = _controller(plane, cooldown_s=0.5)
    plane.queue = [0] * 40
    ctl.run_once(now=1000.0)
    assert ctl.run_once(now=1005.0)["action"] == "up"
    # past cooldown but the sustain clock restarted at the action
    d = ctl.run_once(now=1006.0)
    assert d["action"] == "hold"
    d = ctl.run_once(now=1011.1)
    assert d["action"] == "up"


def test_force_bypasses_sustain_and_cooldown_not_clamps():
    clock = FakeClock()
    plane = StubPlane(clock, live=2)
    ctl = _controller(plane, max_workers=3, cooldown_s=1000.0)
    d = ctl.force("up", step=5, rule="chaos")
    assert d["forced"] and d["action"] == "up"
    assert plane.spawned == [2]          # clamped to max=3
    d = ctl.force("down", victims=[0], rule="chaos")
    assert plane.drained == [0]
    assert plane.metrics.events["forced"] == 2
    with pytest.raises(ValueError):
        ctl.force("sideways")


def test_ensure_min_spawns_up_to_floor():
    clock = FakeClock()
    plane = StubPlane(clock, live=0)
    plane.workers = {}
    ctl = _controller(plane, min_workers=3)
    assert ctl.ensure_min() == [0, 1, 2]
    assert sorted(plane.workers) == [0, 1, 2]
    assert ctl.ensure_min() == []        # already at floor


def test_status_and_decision_log():
    clock = FakeClock()
    plane = StubPlane(clock, live=2)
    ctl = _controller(plane, min_workers=1, max_workers=8)
    st = ctl.status()
    assert st["min"] == 1 and st["max"] == 8
    assert st["running"] is False and st["last_decision"] is None
    ctl.force("up", rule="probe")
    st = ctl.status()
    assert st["decisions"] == 1
    assert st["last_decision"]["rule"] == "probe"
    assert plane.autoscaler is ctl       # registered for health()


def test_config_scale_knobs(monkeypatch):
    from rca_tpu.config import (
        fed_scale_cooldown_s,
        fed_scale_max,
        fed_scale_min,
    )

    monkeypatch.setenv("RCA_FED_SCALE_MIN", "3")
    monkeypatch.setenv("RCA_FED_SCALE_MAX", "12")
    monkeypatch.setenv("RCA_FED_SCALE_COOLDOWN_S", "2.5")
    assert fed_scale_min() == 3
    assert fed_scale_max() == 12
    assert fed_scale_cooldown_s() == 2.5


# ---------------------------------------------------------------------------
# Placement + drain scale-down vs FAKE workers (real wire protocol)
# ---------------------------------------------------------------------------


class FakeWorker:
    """In-process worker over a loopback socket; ``registry`` /
    ``headroom`` ride the hello as placement evidence."""

    def __init__(self, worker_id, plane, registry=None, headroom=None,
                 heartbeat_s=0.05):
        self.worker_id = worker_id
        self.heartbeat_s = heartbeat_s
        self.lease_id = None
        self.served = 0
        self.drain_seen = 0
        self._lock = make_lock("FakeWorker._lock")
        sock = make_client_socket(
            f"fake{worker_id}", plane.host, plane.port
        )
        self.conn = FrameConn(sock, name=f"fake{worker_id}")
        hello = {
            "t": "hello", "proto": PROTO, "worker_id": worker_id,
            "pid": 0, "engine": "fake",
        }
        if registry is not None:
            hello["registry"] = registry
        if headroom is not None:
            hello["headroom"] = headroom
        self.conn.send(hello)
        self._reader = spawn(
            self._read_loop, name=f"fake{worker_id}-read", daemon=True,
        )
        self._hb = spawn(
            self._hb_loop, name=f"fake{worker_id}-hb", daemon=True,
        )

    def _read_loop(self):
        while True:
            try:
                msg = self.conn.recv()
            except (FrameError, OSError):
                return
            if msg is None:
                return
            t = msg.get("t")
            if t == "lease":
                with self._lock:
                    self.lease_id = msg["lease_id"]
            elif t == "req":
                self.conn.send({
                    "t": "resp", "request_id": msg["request_id"],
                    "status": "ok",
                    "ranked": [{"component": f"svc-{self.worker_id}",
                                "score": 1.0}],
                    "batch_size": 1, "engine": "fake",
                })
                self.served += 1
            elif t == "drain":
                with self._lock:
                    self.drain_seen += 1
                self.conn.send({"t": "drained", "served": self.served})

    def _hb_loop(self):
        seq = 0
        while not self.conn.closed:
            time.sleep(self.heartbeat_s)
            with self._lock:
                lease = self.lease_id
            if lease is None:
                continue
            seq += 1
            if not self.conn.send({
                "t": "hb", "worker_id": self.worker_id,
                "lease_id": lease, "seq": seq,
            }):
                return

    def close(self):
        self.conn.close()


def _plane(**kw):
    kw.setdefault("heartbeat_s", 0.05)
    kw.setdefault("lease_misses", 3)
    plane = FederationPlane(workers=1, spawn_workers=False, **kw)
    plane.start()
    return plane


def _wait(pred, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def test_placement_prefers_winning_timings_and_headroom():
    """A mid-bucket request routes to the worker whose hello advertises
    the winning timing at its shape tier — deterministically, so the
    bucket stays sticky; headroom breaks a timing tie."""
    plane = _plane()
    fakes = [
        FakeWorker(0, plane),                                # no evidence
        FakeWorker(1, plane, registry={"64": 5.0},
                   headroom={"bytes_in_use": 10}),
        FakeWorker(2, plane, registry={"64": 2.0},
                   headroom={"bytes_in_use": 100}),
    ]
    try:
        assert plane.wait_ready(3, timeout_s=10.0)
        reqs = [_req(n=48, seed=i) for i in range(4)]
        for r in reqs:
            plane.submit(r)
        rs = [r.result(10.0) for r in reqs]
        assert all(r.status == "ok" for r in rs)
        # n=48 hits the mid-graphs bucket (timings): worker 2 wins
        assert {r.ranked[0]["component"] for r in rs} == {"svc-2"}
        snap = plane.metrics.snapshot()
        assert snap["placement"]["preferred"] >= 4
        # timing tie in the BIG bucket (headroom evidence enabled) →
        # smaller bytes_in_use (more headroom) wins
        with plane._lock:
            plane.workers[2].shape_ms = {64: 5.0}
        tied = [_req(n=200, seed=9) for _ in range(3)]
        for r in tied:
            plane.submit(r)
        out = [r.result(10.0) for r in tied]
        assert {r.ranked[0]["component"] for r in out} == {"svc-1"}
    finally:
        for f in fakes:
            f.close()
        plane.stop()


def test_placement_falls_back_to_rendezvous():
    """No evidence anywhere (and small-bucket requests regardless) →
    pure rendezvous, counted as such."""
    plane = _plane()
    fakes = [FakeWorker(i, plane) for i in range(3)]
    try:
        assert plane.wait_ready(3, timeout_s=10.0)
        reqs = [_req(n=48, seed=3) for _ in range(4)]   # ONE graph
        for r in reqs:
            plane.submit(r)
        rs = [r.result(10.0) for r in reqs]
        assert all(r.status == "ok" for r in rs)
        assert len({r.ranked[0]["component"] for r in rs}) == 1  # sticky
        snap = plane.metrics.snapshot()
        assert snap["placement"]["rendezvous"] >= 4
        assert snap["placement"]["preferred"] == 0
        # small graphs never consult evidence, even when present
        with plane._lock:
            plane.workers[0].shape_ms = {64: 0.1}
        small = _req(n=8, seed=5)
        plane.submit(small)
        assert small.result(10.0).status == "ok"
        assert plane.metrics.snapshot()["placement"]["preferred"] == 0
    finally:
        for f in fakes:
            f.close()
        plane.stop()


def test_drain_scale_down_is_never_process_kill():
    """drain_worker retires a member through drain-and-reroute: the
    worker answers ``drained``, the handle completes as
    ``worker_scaled_down``, and the socket closing afterwards must NOT
    read as a ``process_kill`` death."""
    plane = _plane()
    fakes = [FakeWorker(i, plane) for i in range(2)]
    try:
        assert plane.wait_ready(2, timeout_s=10.0)
        assert plane.drain_worker(0) is True
        assert _wait(lambda: any(
            e["event"] == "worker_scaled_down" and e["worker_id"] == 0
            for e in list(plane.events)
        ))
        assert plane.drain_worker(0) is False    # already retired
        assert plane.drain_worker(99) is False   # unknown
        fakes[0].close()                         # EOF after retirement
        time.sleep(0.2)
        downs = [e for e in list(plane.events)
                 if e["event"] == "worker_down" and e["worker_id"] == 0]
        assert downs == []                       # retirement, not death
        status = plane.scale_status()
        assert status["live"] == [1]
        assert status["next_id"] == 2            # ids never reused
        # the survivor still serves
        r = _req(seed=1)
        plane.submit(r)
        assert r.result(10.0).status == "ok"
    finally:
        for f in fakes:
            f.close()
        plane.stop()


def test_health_carries_fleet_and_autoscale():
    plane = _plane()
    fakes = [FakeWorker(0, plane, registry={"64": 1.0})]
    ctl = AutoscaleController(plane, min_workers=1, max_workers=4)
    try:
        assert plane.wait_ready(1, timeout_s=10.0)
        h = plane.health()
        assert [w["worker_id"] for w in h["fleet"]] == [0]
        assert h["fleet"][0]["shapes_known"] == 1
        assert h["fleet"][0]["draining"] is False
        assert h["autoscale"]["min"] == 1
        assert h["autoscale"]["max"] == 4
    finally:
        ctl.stop()
        for f in fakes:
            f.close()
        plane.stop()


# ---------------------------------------------------------------------------
# Worker rejoin backoff (the ISSUE 16 small fix) — wire-level regression
# ---------------------------------------------------------------------------


def test_rejoin_backoff_distinct_seeded_delays():
    """A stale-lease reject storm must produce DISTINCT, growing,
    seeded sleep delays before each re-hello — not an immediate-retry
    stampede."""
    from rca_tpu.serve.worker import (
        REJOIN_BACKOFF_BASE_S,
        REJOIN_BACKOFF_CAP_S,
        WorkerAgent,
    )
    from rca_tpu.util.net import bound_address, make_server_socket

    srv = make_server_socket("backoff-test", "127.0.0.1", 0)
    host, port = bound_address(srv)
    frames = []

    class DummyLoop:
        def submit(self, req):
            pass

    def coordinator():
        sock, _ = srv.accept()
        conn = FrameConn(sock, name="backoff-coord")
        rejects = 0
        while True:
            msg = conn.recv()
            if msg is None:
                return
            frames.append(msg)
            if msg.get("t") == "hello":
                if rejects < 3:
                    rejects += 1
                    conn.send({"t": "reject", "reason": "stale_lease"})
                else:
                    conn.send({"t": "lease", "lease_id": "L",
                               "ttl_s": 1.0, "heartbeat_s": 10.0})
                    conn.send({"t": "drain"})

    coord = spawn(coordinator, name="backoff-coord", daemon=True)
    slept = []
    agent = WorkerAgent(
        0, host, port, DummyLoop(), rejoin_seed=5,
        sleeper=slept.append,
    )
    try:
        assert agent.run() == 0          # drained cleanly in the end
    finally:
        agent.close()
        srv.close()
        coord.join(5.0)
    assert len(slept) == 3
    assert len(set(slept)) == 3          # DISTINCT delays
    assert slept == agent.rejoin_delays
    for i, d in enumerate(slept):
        raw = min(REJOIN_BACKOFF_CAP_S, REJOIN_BACKOFF_BASE_S * 2.0 ** i)
        assert 0.5 * raw <= d <= 1.5 * raw
    # seeded: the same seed replays the same spread
    import random

    rng = random.Random(5)
    expect = [
        min(REJOIN_BACKOFF_CAP_S, REJOIN_BACKOFF_BASE_S * 2.0 ** i)
        * (0.5 + rng.random())
        for i in range(3)
    ]
    assert slept == pytest.approx(expect)
    # the re-hellos carried no stale lease
    hellos = [f for f in frames if f.get("t") == "hello"]
    assert len(hellos) == 4
    assert all("lease_id" not in h for h in hellos)


# ---------------------------------------------------------------------------
# The 2→8→2 load-ramp soak (acceptance gate, real thread workers)
# ---------------------------------------------------------------------------


def test_scale_ramp_soak_2_8_2():
    """The tentpole contract: under continuous traffic the fleet walks
    2→8→2 with every request terminal, ZERO double completions, and the
    windowed queue p99 bounded through both transitions."""
    out = run_scale_ramp_soak(seed=0, min_workers=2, max_workers=8)
    assert out["ok"], out
    assert out["all_terminal"]
    assert out["double_completions"] == 0
    assert out["peaked"] and out["shrunk"]
    assert out["scale_ups"] >= 1 and out["scale_downs"] >= 1
    assert out["p99_ok"]
    assert out["by_status"].get("hung", 0) == 0
    assert out["requests"] == sum(out["by_status"].values())


@pytest.mark.slow
def test_scaling_storm_chaos_gate():
    """The chaos gate `rca chaos` runs: every forced transition racing
    a fault seam observed, zero doubles, bounded stale drops."""
    out = run_scaling_storm(seed=0)
    assert out["ok"], out
    assert "scaling_storm" in out["fault_classes_observed"]
    assert out["double_completions"] == 0
    assert out["stale_responses"] <= out["stale_bound"]
