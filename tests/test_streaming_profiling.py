"""Streaming session ticks, stage profiling, per-analysis viz payloads."""

import numpy as np

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.engine.streaming import StreamingSession
from rca_tpu.obslog.profiling import StageTimer
from rca_tpu.ui.render import analysis_viz_data, wizard_stage_markdown


def test_streaming_session_tracks_fault_changes():
    case = synthetic_cascade_arrays(300, n_roots=1, seed=7)
    names = case.names
    sess = StreamingSession(
        names, case.dep_src, case.dep_dst,
        num_features=case.features.shape[1], k=3,
    )
    sess.set_all(case.features)
    out1 = sess.tick()
    assert out1["tick"] == 1
    assert out1["latency_ms"] > 0
    root = case.names[case.roots[0]]
    assert out1["ranked"][0]["component"] == root

    # inject a second concurrent hard failure -> both roots rank top-2
    new_root = (case.roots[0] + 137) % case.n
    second = case.features.copy()
    second[new_root, 0] = 1.0   # CRASH channel
    second[new_root, 3] = 0.9   # RESTARTS
    sess.set_all(second)
    out2 = sess.tick()
    assert out2["tick"] == 2
    top2 = {r["component"] for r in out2["ranked"][:2]}
    assert top2 == {root, case.names[new_root]}

    # delta update path: clearing just the new fault restores the ranking
    sess.update(int(new_root), case.features[new_root])
    out3 = sess.tick()
    assert out3["ranked"][0]["component"] == root
    assert case.names[new_root] not in {
        r["component"] for r in out3["ranked"][:2]
    }


def test_streaming_delta_uploads_proportional_and_exact():
    """SURVEY §7 / BASELINE row 4: per-tick upload is proportional to the
    delta count (padded-pow2 rows, not the [S, C] matrix), a quiet tick
    uploads nothing, and the delta path lands on exactly the state a full
    re-upload would."""
    case = synthetic_cascade_arrays(1000, n_roots=1, seed=3)
    sess = StreamingSession(
        case.names, case.dep_src, case.dep_dst,
        num_features=case.features.shape[1], k=3,
    )
    sess.set_all(case.features)
    first = sess.tick()
    # the bulk set_all upload is accounted on its first tick
    assert first["upload_rows"] == sess._n_pad

    # quiet tick: no host->device rows at all
    assert sess.tick()["upload_rows"] == 0

    # 10 changed services -> 16 padded rows, NOT 1000
    changed = {(case.roots[0] + 31 * j) % case.n: np.full(
        case.features.shape[1], 0.5, np.float32
    ) for j in range(10)}
    sess.update_many(changed)
    out = sess.tick()
    assert out["upload_rows"] == 16

    # exactness: a fresh session fed the same final state ranks identically
    full = case.features.copy()
    for i, row in changed.items():
        full[i] = row
    ref = StreamingSession(
        case.names, case.dep_src, case.dep_dst,
        num_features=case.features.shape[1], k=3,
    )
    ref.set_all(full)
    expected = ref.tick()
    assert [r["component"] for r in out["ranked"]] == [
        r["component"] for r in expected["ranked"]
    ]
    np.testing.assert_allclose(
        [r["score"] for r in out["ranked"]],
        [r["score"] for r in expected["ranked"]],
        rtol=1e-6,
    )


def test_stage_timer_report():
    t = StageTimer()
    with t.stage("a"):
        pass
    with t.stage("b"):
        with t.stage("a"):
            pass
    rep = t.report()
    assert set(rep) == {"a", "b", "total_ms"}
    assert rep["total_ms"] >= rep["a"]


def test_comprehensive_carries_profile():
    from rca_tpu.agents import AnalysisContext
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.coordinator import RCACoordinator

    coord = RCACoordinator(MockClusterClient(five_service_world()))
    ctx = AnalysisContext(ClusterSnapshot.capture(
        MockClusterClient(five_service_world()), NS
    ))
    rec = coord.run_analysis("comprehensive", NS, ctx=ctx)
    profile = rec["results"]["profile"]
    assert "correlate" in profile
    assert "agent.topology" in profile
    assert profile["total_ms"] > 0


def test_analysis_viz_payloads():
    logs_result = {
        "findings": [
            {"component": "Pod/x", "severity": "high",
             "evidence": {"pattern": "oom_kill", "count": 3}},
            {"component": "Pod/y", "severity": "high",
             "evidence": {"pattern": "oom_kill", "count": 2}},
        ],
    }
    viz = analysis_viz_data("logs", logs_result)
    assert viz["severity_histogram"] == {"high": 2}
    assert viz["pattern_counts"] == {"oom_kill": 5}

    res_result = {"findings": [], "data": {"pod_buckets": {"crashloop": 1}}}
    assert analysis_viz_data("resources", res_result)["pod_buckets"] == {
        "crashloop": 1
    }

    traces_result = {
        "findings": [
            {"component": "Service/a", "severity": "high",
             "evidence": {"error_rate": 0.25}},
        ],
    }
    viz = analysis_viz_data("traces", traces_result)
    assert viz["error_rates"][0]["error_rate"] == 0.25


def test_wizard_stage_markdown():
    md = wizard_stage_markdown({"stage": 2})
    assert "▶️ Investigate" in md
    assert md.count("✅") == 2


def test_live_streaming_session_tracks_world_changes():
    """Cluster → feature diff → delta upload → fused tick: a healthy world
    polls with zero changed rows; injecting a crash re-ranks the crashed
    service to the top with only the changed rows uploaded; fixing it
    drops it back."""
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.world import waiting_status
    from rca_tpu.engine import LiveStreamingSession

    world = synthetic_cascade_world(40, n_roots=1, seed=3,
                                    namespace="stream")
    client = MockClusterClient(world)
    live = LiveStreamingSession(client, "stream", k=3)
    root = world.ground_truth["fault_roots"][0]

    out1 = live.poll()
    assert out1["resynced"] is False
    assert out1["changed_rows"] == 0  # frozen world: nothing changed
    assert out1["ranked"][0]["component"] == root

    # victim pod of a previously-healthy service starts crash-looping
    victim_svc = next(
        n for n in live._names if n != root and not n.startswith(root)
    )
    pod = next(
        p for p in world.pods["stream"]
        if p["metadata"]["labels"].get("app") == victim_svc
    )
    pod["status"]["phase"] = "Running"
    pod["status"]["containerStatuses"] = [
        waiting_status(victim_svc, "CrashLoopBackOff",
                       restarts=9, last_exit_code=1)
    ]
    # direct dict edits are out-of-band for the watch feed — notify it,
    # as every API-server-mediated mutation would be
    world.touch("pod", "stream", pod["metadata"]["name"])
    out2 = live.poll()
    assert out2["resynced"] is False
    assert 1 <= out2["changed_rows"] <= 3  # only the mutated service moved
    assert out2["upload_rows"] >= out2["changed_rows"]
    top2 = {r["component"] for r in out2["ranked"][:2]}
    assert victim_svc in top2 and root in top2

    # revert: the service heals, ranking recovers
    pod["status"]["containerStatuses"] = [
        {"name": victim_svc, "ready": True, "restartCount": 0,
         "state": {"running": {}}}
    ]
    world.touch("pod", "stream", pod["metadata"]["name"])
    out3 = live.poll()
    assert out3["ranked"][0]["component"] == root
    assert victim_svc not in {r["component"] for r in out3["ranked"][:1]}


def test_live_streaming_session_resyncs_on_topology_change():
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.world import make_deployment
    from rca_tpu.engine import LiveStreamingSession

    world = five_service_world()
    client = MockClusterClient(world)
    live = LiveStreamingSession(client, NS, k=3)
    assert live.resyncs == 0
    n0 = len(live._names)

    # a brand-new service appears -> topology changed -> full rebuild
    # (World.add journals the change, so the watch feed reports it)
    world.add("services", NS, {
        "metadata": {"name": "newsvc", "namespace": NS},
        "spec": {"selector": {"app": "newsvc"},
                 "ports": [{"port": 80}]},
    })
    world.add("deployments", NS, make_deployment("newsvc", NS, "newsvc"))
    out = live.poll()
    assert out["resynced"] is True
    assert live.resyncs == 1
    assert len(live._names) == n0 + 1
    assert out["ranked"]  # still ranks after the rebuild
    # tick counter is session-lifetime: monotonic ACROSS the resync (the
    # inner StreamingSession restarts at 1; the CLI/UI sequence must not)
    assert out["tick"] == 1
    out2 = live.poll()
    assert out2["resynced"] is False
    assert out2["tick"] == 2


def test_set_all_upload_accounted_on_next_tick():
    """A resync's bulk upload must show up in upload_rows, not read as 0
    (bandwidth accounting would otherwise miss the most expensive upload
    of the session)."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine.streaming import StreamingSession

    sk = synthetic_cascade_arrays(30, n_roots=1, seed=0)
    sess = StreamingSession(
        [f"s{i}" for i in range(sk.n)], sk.dep_src, sk.dep_dst,
        num_features=sk.features.shape[1], k=3,
    )
    sess.set_all(sk.features)
    out = sess.tick()
    assert out["upload_rows"] == sess._n_pad  # the bulk path, once
    out = sess.tick()
    assert out["upload_rows"] == 0  # steady state
    # set_all followed by a delta before the tick: both counted
    sess.set_all(sk.features)
    sess.update(0, np.zeros(sk.features.shape[1], np.float32))
    out = sess.tick()
    assert out["upload_rows"] == sess._n_pad + 1


def test_live_streaming_edge_only_change_caught_by_periodic_check():
    """Same service set, new dependency edge: caught within
    topology_check_every polls (the cheap name check can't see it)."""
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine import LiveStreamingSession

    world = five_service_world()
    client = MockClusterClient(world)
    live = LiveStreamingSession(client, NS, k=3, topology_check_every=2)

    # add a dependency edge without changing the service set: frontend's
    # traces now report a call into resource-service
    world.traces["dependencies"][NS]["frontend"] = list(
        world.traces["dependencies"][NS].get("frontend", [])
    ) + ["resource-service"]
    out1 = live.poll()  # poll 1: no edge check scheduled
    assert out1["resynced"] is False
    out2 = live.poll()  # poll 2: periodic edge check fires
    assert out2["resynced"] is True
    assert live.resyncs == 1
