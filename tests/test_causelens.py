"""causelens (ISSUE 14): evidence attribution + blame-path provenance.

The contract under test:

- **completeness axiom**: per-channel contributions reconstruct
  ``combine_score`` within 1e-5 (float32 kernels), at three shapes;
- **rank stability**: blame ordering (candidates, counterfactual order,
  blame-path nodes) is identical across the ``xla | segscan | doubling``
  kernels and invariant under ``RCA_TRACE``;
- **surfaces**: lazy ``EngineResult.attribution()``, serve
  ``ServeRequest.explain`` + per-tenant metrics, gateway ``?explain=1``
  + ``GET /v1/explain/<id>`` + ``/metrics`` family, findings provenance,
  ``rca why`` rendering, and the registry's ``attribution`` variant row;
- **replay**: per-tick attribution digests recorded with
  ``RCA_EXPLAIN=1`` parity-check from the tape (``rca replay
  --explain``), including through a 40-tick chaos soak where degraded
  ticks must still carry finite attributions.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from rca_tpu.cluster.generator import (
    synthetic_cascade_arrays,
    synthetic_cascade_world,
)
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.engine.registry import kernel_table, reset_registry
from rca_tpu.engine.runner import EngineResult, GraphEngine

RECONSTRUCTION_TOL = 1e-5


def _analyze(n=48, seed=3, k=5, engine=None):
    case = synthetic_cascade_arrays(n, n_roots=1, seed=seed)
    engine = engine or GraphEngine()
    res = engine.analyze_arrays(
        case.features, case.dep_src, case.dep_dst, case.names, k=k,
    )
    return case, res


# -- completeness axiom -------------------------------------------------------

@pytest.mark.parametrize("n", [24, 96, 300])
def test_completeness_reconstruction(n):
    """Per-channel contributions rebuild a, and a × impact ×
    suppression rebuilds the combined score within 1e-5 — at three
    shapes (three padded tiers)."""
    _case, res = _analyze(n=n, seed=7)
    block = res.attribution()["attribution"]
    assert block["schema"] == 1
    assert block["candidates"], "no candidates attributed"
    for cand in block["candidates"]:
        assert cand["reconstruction_error"] <= RECONSTRUCTION_TOL, cand
        # the factors the reconstruction multiplies are the block's own
        f = cand["factors"]
        rebuilt = f["evidence"] * f["impact"] * f["suppression"]
        assert abs(rebuilt - cand["score"]) <= RECONSTRUCTION_TOL
    # the block is finite everywhere (json with allow_nan=False raises
    # on any NaN/Inf) and deterministically digestable
    json.dumps(block, allow_nan=False)
    assert res.attribution()["digest"]


def test_attribution_deterministic_and_cached():
    _case, res = _analyze(seed=5)
    first = res.attribution()
    assert res.attribution() is first          # cached per result
    _case2, res2 = _analyze(seed=5)            # fresh result, same inputs
    assert res2.attribution()["digest"] == first["digest"]


def test_attribution_requires_context():
    bare = EngineResult(["a"], [], 0.0, 1, 0)
    with pytest.raises(ValueError):
        bare.attribution()


def test_counterfactual_self_mask_drops_own_score():
    """Masking the top candidate's own evidence row must drop its score
    by (approximately) the whole score — the strongest counterfactual
    names itself."""
    _case, res = _analyze(n=48, seed=3)
    top = res.attribution()["attribution"]["candidates"][0]
    self_cf = [c for c in top["counterfactuals"] if c["self"]]
    assert self_cf, "top candidate's own row was not in the mask set"
    assert self_cf[0]["score_drop"] == max(
        c["score_drop"] for c in top["counterfactuals"]
    )


# -- registry: the attribution variant ---------------------------------------

def test_registry_attribution_variant_row():
    _case, res = _analyze(n=48, seed=3)
    res.attribution()
    rows = [r for r in kernel_table() if r["variant"] == "attribution"]
    assert rows, "attribution dispatch left no registry row"
    row = rows[0]
    assert row["winner"] == "xla"
    assert row["source"] == "attribution"
    # every non-xla kernel names WHY it sat out
    for kern in ("pallas", "segscan", "quantized", "doubling"):
        assert isinstance(row["eligible"][kern], str)
    # the observed per-shape cost landed in the row's timings
    assert row["timings_ms"].get("attribution") is not None


# -- rank stability across kernels and knobs ---------------------------------

def _blame_key(prov):
    return [
        (
            c["component"],
            tuple(e["component"] for e in c["counterfactuals"]),
            tuple(h["to"] for h in c["blame_path"]),
        )
        for c in prov["attribution"]["candidates"]
    ]


def test_blame_order_rank_stable_across_kernels(monkeypatch):
    """The attribution sweep runs through its own registry variant, so
    the blame ordering must be IDENTICAL whichever serving kernel the
    ranking came from (xla | segscan | doubling)."""
    case = synthetic_cascade_arrays(96, n_roots=1, seed=9)
    outs = {}
    try:
        for kern in ("xla", "segscan", "doubling"):
            monkeypatch.setenv("RCA_KERNEL", kern)
            reset_registry()
            res = GraphEngine().analyze_arrays(
                case.features, case.dep_src, case.dep_dst, case.names,
                k=5,
            )
            outs[kern] = (_blame_key(res.attribution()),
                          res.attribution()["digest"])
    finally:
        monkeypatch.delenv("RCA_KERNEL", raising=False)
        reset_registry()
    assert outs["xla"][0] == outs["segscan"][0] == outs["doubling"][0]
    assert outs["xla"][1] == outs["segscan"][1] == outs["doubling"][1]


def test_attribution_invariant_under_trace():
    """RCA_TRACE must not move an attribution bit: a traced session and
    a null-tracer session produce identical per-tick digests."""
    from rca_tpu.engine.live import LiveStreamingSession
    from rca_tpu.observability.spans import Tracer

    def run(tracer):
        world = synthetic_cascade_world(16, n_roots=1, seed=5)
        sess = LiveStreamingSession(
            MockClusterClient(world), "synthetic", k=5,
            tracer=tracer, explain=True,
        )
        return [sess.poll().get("attribution_digest") for _ in range(4)]

    traced = run(Tracer(seed=2))
    untraced = run(None)  # the RCA_TRACE=0 null default
    assert all(traced) and traced == untraced


# -- serve + gateway surfaces -------------------------------------------------

def test_serve_explain_response_and_metrics():
    from rca_tpu.serve import ServeClient, ServeLoop

    case = synthetic_cascade_arrays(48, n_roots=1, seed=3)
    loop = ServeLoop(engine=GraphEngine())
    with loop:
        client = ServeClient(loop)
        r_explained = client.analyze(
            case.features, case.dep_src, case.dep_dst,
            names=case.names, tenant="t1", explain=True,
        )
        r_plain = client.analyze(
            case.features, case.dep_src, case.dep_dst,
            names=case.names, tenant="t1",
        )
    assert r_explained.ok and r_plain.ok
    assert r_explained.provenance is not None
    assert r_explained.provenance["schema"] == 1
    assert r_explained.provenance["attribution"]["candidates"]
    assert r_plain.provenance is None
    tenants = loop.metrics.summary()["tenants"]
    assert tenants["t1"]["explain_requests"] == 1
    # rankings are unaffected by the explain flag
    assert r_explained.ranked == r_plain.ranked


def test_gateway_explain_query_endpoint_and_metrics():
    import http.client

    from rca_tpu.gateway import GatewayServer
    from rca_tpu.gateway.wire import encode_analyze
    from rca_tpu.serve import ServeLoop

    case = synthetic_cascade_arrays(32, n_roots=1, seed=3)
    loop = ServeLoop(engine=GraphEngine()).start()
    gw = GatewayServer(loop, port=0).start()
    try:
        conn = http.client.HTTPConnection(gw.host, gw.port, timeout=60)
        body = json.dumps(encode_analyze(
            case.features, case.dep_src, case.dep_dst,
            names=list(case.names),
        )).encode()
        conn.request("POST", "/v1/analyze?explain=1", body,
                     {"X-RCA-Tenant": "wire-t"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200, out
        assert out["provenance"]["schema"] == 1
        # retained for the follow-up read, keyed by request id (tracing
        # is off here) — and a miss is an honest 404
        conn.request("GET", f"/v1/explain/{out['request_id']}")
        r2 = conn.getresponse()
        o2 = json.loads(r2.read())
        assert r2.status == 200
        assert o2["provenance"] == out["provenance"]
        conn.request("GET", "/v1/explain/absent")
        r3 = conn.getresponse()
        r3.read()
        assert r3.status == 404
        # body-field twin of the query param
        body2 = json.dumps(encode_analyze(
            case.features, case.dep_src, case.dep_dst,
            names=list(case.names), explain=True,
        )).encode()
        conn.request("POST", "/v1/analyze", body2,
                     {"X-RCA-Tenant": "wire-t"})
        r4 = conn.getresponse()
        o4 = json.loads(r4.read())
        assert o4["provenance"]["schema"] == 1
        # un-explained requests carry no provenance
        conn.request("POST", "/v1/analyze", body)
        r5 = conn.getresponse()
        o5 = json.loads(r5.read())
        assert "provenance" not in o5
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        assert 'rca_explain_requests_total{tenant="wire-t"} 2' in text
    finally:
        gw.close()
        loop.stop()


def test_wire_decode_explain():
    from rca_tpu.gateway.wire import WireError, decode_analyze

    base = {
        "features": [[0.0, 1.0]], "dep_src": [], "dep_dst": [],
    }
    assert decode_analyze(dict(base))["explain"] is False
    assert decode_analyze({**base, "explain": True})["explain"] is True
    with pytest.raises(WireError):
        decode_analyze({**base, "explain": "yes"})


# -- findings / coordinator / rca why ----------------------------------------

def test_correlate_jax_attaches_provenance(monkeypatch):
    from rca_tpu.agents.base import AnalysisContext
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.coordinator.correlate import correlate_jax

    monkeypatch.setenv("RCA_EXPLAIN", "1")
    client = MockClusterClient(five_service_world())
    snap = ClusterSnapshot.capture(client, NS)
    ctx = AnalysisContext(snapshot=snap)
    out = correlate_jax({}, ctx, top_k=5)
    assert out["provenance"]["schema"] == 1
    assert out["provenance"]["attribution"]["candidates"]
    monkeypatch.delenv("RCA_EXPLAIN")
    out2 = correlate_jax({}, ctx, top_k=5)
    assert "provenance" not in out2


def test_attach_provenance_schema_checked():
    from rca_tpu.findings import attach_provenance

    assert attach_provenance({}, None) == {}
    with pytest.raises(ValueError):
        attach_provenance({}, {"not": "versioned"})
    out = attach_provenance({}, {"schema": 1, "attribution": {}})
    assert out["provenance"]["schema"] == 1


def test_rca_why_renders_blame_tree(tmp_path, capsys):
    """The end-to-end `rca why` path: an explained serve request naming
    an investigation stamps provenance into the store; the CLI renders
    the blame tree from it."""
    from rca_tpu.cli import main as cli_main
    from rca_tpu.serve import ServeClient, ServeLoop
    from rca_tpu.store import InvestigationStore

    root = str(tmp_path / "logs")
    store = InvestigationStore(root=root)
    inv = store.create_investigation("causelens test", namespace="synthetic")
    case = synthetic_cascade_arrays(48, n_roots=1, seed=3)
    loop = ServeLoop(engine=GraphEngine(), store=store)
    with loop:
        resp = ServeClient(loop).analyze(
            case.features, case.dep_src, case.dep_dst, names=case.names,
            tenant="t1", explain=True, investigation_id=inv["id"],
        )
    assert resp.ok
    assert store.get_provenance(inv["id"]) is not None
    assert cli_main(["why", inv["id"], "--log-dir", root]) == 0
    text = capsys.readouterr().out
    assert "blame path" in text
    assert resp.ranked[0]["component"] in text
    # --json prints the raw block
    assert cli_main(["why", inv["id"], "--log-dir", root, "--json"]) == 0
    block = json.loads(capsys.readouterr().out)
    assert block["schema"] == 1
    # missing provenance / missing investigation are loud
    inv2 = store.create_investigation("empty", namespace="synthetic")
    assert cli_main(["why", inv2["id"], "--log-dir", root]) == 1
    capsys.readouterr()
    assert cli_main(["why", "nope", "--log-dir", root]) == 1
    capsys.readouterr()


# -- replay parity ------------------------------------------------------------

def test_replay_explain_parity_and_requires_digests(tmp_path):
    from rca_tpu.engine.live import LiveStreamingSession
    from rca_tpu.replay import Recorder, load_recording, replay_stream

    def record(path, explain):
        world = synthetic_cascade_world(16, n_roots=1, seed=5)
        rec = Recorder(path, mode="stream")
        sess = LiveStreamingSession(
            MockClusterClient(world), "synthetic", k=5, recorder=rec,
            explain=explain,
        )
        for _ in range(5):
            out = sess.poll()
            if explain:
                assert out.get("attribution_digest")
        rec.close()

    explained = str(tmp_path / "explained")
    record(explained, explain=True)
    rec = load_recording(explained)
    assert all(
        fr.get("attribution_digest") for fr in rec.ticks.values()
    )
    report = replay_stream(explained, explain=True)
    assert report["parity_ok"]
    assert report["attribution_ticks_compared"] == 5
    assert report["attribution_mismatched_ticks"] == []
    # digests present in the tape are compared even WITHOUT the flag
    report2 = replay_stream(explained)
    assert report2["attribution_ticks_compared"] == 5
    # --explain against an unexplained recording is an honest failure
    plain = str(tmp_path / "plain")
    record(plain, explain=False)
    report3 = replay_stream(plain, explain=True)
    assert not report3["parity_ok"]
    assert "attribution" in report3["attribution_error"]
    # ...and without the flag the unexplained recording still passes
    assert replay_stream(plain)["parity_ok"]


def test_chaos_soak_explained_40_ticks(tmp_path, monkeypatch):
    """The 40-tick chaos leg: with RCA_EXPLAIN=1 every tick — degraded
    ones included — carries a finite attribution digest, the recording
    replays with attribution parity, and poll() never raises."""
    from rca_tpu.replay import load_recording
    from rca_tpu.resilience.chaos import ChaosConfig, run_chaos_soak

    monkeypatch.setenv("RCA_EXPLAIN", "1")
    rec_path = str(tmp_path / "rec")
    summary = run_chaos_soak(
        lambda: synthetic_cascade_world(20, n_roots=1, seed=11),
        "synthetic", seed=14, ticks=40, config=ChaosConfig(seed=14),
        record_path=rec_path,
    )
    assert summary["uncaught_exceptions"] == 0
    assert summary["parity_ok"]
    assert summary["replay"]["parity_ok"]
    assert summary["replay"]["attribution_ticks_compared"] == 40
    assert summary["replay"]["attribution_parity_ok"]
    rec = load_recording(rec_path)
    assert len(rec.ticks) == 40
    for fr in rec.ticks.values():
        # present AND finite on every tick, degraded or not (a digest
        # only exists when the block json-serialized finitely)
        assert fr.get("attribution_digest")


def test_explain_config_knobs(monkeypatch):
    from rca_tpu.config import explain_enabled, explain_paths, explain_topm

    assert explain_enabled() is False
    monkeypatch.setenv("RCA_EXPLAIN", "1")
    assert explain_enabled() is True
    monkeypatch.setenv("RCA_EXPLAIN_PATHS", "6")
    monkeypatch.setenv("RCA_EXPLAIN_TOPM", "16")
    assert explain_paths() == 6
    assert explain_topm() == 16
    monkeypatch.setenv("RCA_EXPLAIN_TOPM", "1000")
    with pytest.raises(ValueError):
        explain_topm()
    monkeypatch.setenv("RCA_EXPLAIN", "maybe")
    with pytest.raises(ValueError):
        explain_enabled()


def test_explain_knobs_shape_the_block():
    case = synthetic_cascade_arrays(64, n_roots=1, seed=4)
    res = GraphEngine().analyze_arrays(
        case.features, case.dep_src, case.dep_dst, case.names, k=3,
    )
    prov = res.attribution(paths=2, topm=3)
    block = prov["attribution"]
    assert block["topm"] == 3 and block["paths"] == 2
    assert len(block["evidence_rows"]) == 3
    for cand in block["candidates"]:
        assert len(cand["counterfactuals"]) == 3
        assert len(cand["blame_path"]) <= 2


def test_render_blame_tree_shapes():
    from rca_tpu.observability.causelens import render_blame_tree

    _case, res = _analyze(n=48, seed=3)
    text = render_blame_tree(res.attribution())
    assert "causelens v1" in text
    assert "blame path" in text
    assert "counterfactuals" in text
    # empty block renders, not crashes
    empty = {"schema": 1, "candidates": [], "k": 0}
    assert "no ranked candidates" in render_blame_tree(
        {"attribution": empty, "schema": 1, "digest": None}
    )
