"""graftspec tests (ISSUE 19): the contract tables are sound and
covering, each new rule fires on its fixture and stays silent on the
clean twin, the repo itself sweeps clean, specsan agrees with the static
model on real recorded workloads, and the satellite mechanics (atomic
index publish, shared parse cache) behave."""

import json
import os

import pytest

from rca_tpu.analysis.core import (
    index_path,
    load_index,
    parse_cache_stats,
    parse_file,
    run_lint,
    update_index,
)
from rca_tpu.analysis.dataplane import absint, contracts
from rca_tpu.analysis.dataplane.specsan import (
    SpecsanRecorder,
    capture,
    confirm_findings,
    unify_roles,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(ROOT, "tests", "corpus")


def _fake_repo(tmp_path, *entries):
    for rel, src in entries:
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(src)
    return str(tmp_path)


def _lint(root, rules):
    return run_lint(root=root, rules=rules, use_baseline=False)


# -- contract tables ---------------------------------------------------------

def test_budget_domination_proof_holds():
    """Every FETCH_BUDGETS row must fit its declared byte budget at
    EVERY symbol-grid binding — the table itself is checked, not
    trusted."""
    assert contracts.budget_violations() == []


def test_every_allowlisted_fetch_surface_has_a_budget():
    """Acceptance criterion: residentfetch.FETCH_SURFACES and
    FETCH_BUDGETS must agree — an audited surface without a quantified
    budget is an unquantified contract."""
    assert contracts.coverage() == []


def test_budget_violation_detected():
    """A deliberately under-declared budget is caught by the proof."""
    bad = contracts.FetchBudget(
        (contracts.Role("vals", ("k",), "float32"),), "2*k",
    )
    key = ("rca_tpu/engine/fake.py", "fake_fetch")
    contracts.FETCH_BUDGETS[key] = bad
    try:
        out = contracts.budget_violations()
    finally:
        del contracts.FETCH_BUDGETS[key]
    assert any(v["surface"].endswith("fake_fetch") for v in out)


def test_role_name_normalization():
    assert contracts.role_name("_stacked_dev") == "stacked"
    assert contracts.role_name("vals_h") == "vals"
    assert contracts.role_name("topi") == "idx"
    assert contracts.role_name("n_bad") == "n_bad"


# -- shape-contract ----------------------------------------------------------

def test_shape_contract_pad_and_staging_fixtures(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/engine/ell.py", """\
import numpy as np

def build(n, m, seg):
    n_pad = n + 1                                        # not provable
    e_pad = bucket_for(m)                                # provable
    o_pad = max(8, len(seg))                             # the r19 bug
    q_pad = max(8, 1 << max(0, (m - 1).bit_length()))    # the r19 fix
    bad_fill = np.full(8, 0, np.int32)                   # literal row id
    no_dtype = np.zeros((8, 4))                          # host float64
    ok_fill = np.full(8, n_pad - 1, np.int32)
    ok_buf = np.zeros((8, 4), np.float32)
    return n_pad, e_pad, o_pad, q_pad, bad_fill, no_dtype, ok_fill, ok_buf
"""))
    result = _lint(root, ["shape-contract"])
    lines = sorted(f.line for f in result.findings)
    assert lines == [4, 6, 8, 9], [
        (f.line, f.message) for f in result.findings
    ]


def test_shape_contract_jit_signature_conformance(tmp_path):
    """A conforming _propagate_ranked proves its declared signature; a
    twin returning (idx, vals) swapped breaks the dtype contract."""
    good = """\
import jax.numpy as jnp
from jax import lax

def _propagate_ranked(features, edges, anomaly_w, hard_w, k):
    anomaly, upstream, impact, score, resid = propagate_auto(
        features, edges, anomaly_w, hard_w)
    features, n_bad = finite_mask_rows(features)
    stacked = jnp.stack((anomaly, upstream, impact, score))
    vals, idx = lax.top_k(score, k)
    diag = stacked[:, idx]
    return stacked, diag, vals, idx, n_bad
"""
    root = _fake_repo(tmp_path, ("rca_tpu/engine/runner.py", good))
    assert _lint(root, ["shape-contract"]).findings == []

    bad = good.replace(
        "return stacked, diag, vals, idx, n_bad",
        "return stacked, diag, idx, vals, n_bad",
    )
    root2 = _fake_repo(tmp_path / "swapped", ("rca_tpu/engine/runner.py",
                                              bad))
    msgs = [f.message for f in _lint(root2, ["shape-contract"]).findings]
    assert any("jit signature contract" in m for m in msgs), msgs


def test_shape_contract_arity_break(tmp_path):
    src = """\
def _propagate_ranked(features, edges, anomaly_w, hard_w, k):
    features, n_bad = finite_mask_rows(features)
    return features, n_bad
"""
    root = _fake_repo(tmp_path, ("rca_tpu/engine/runner.py", src))
    msgs = [f.message for f in _lint(root, ["shape-contract"]).findings]
    assert any("returns 2 values" in m for m in msgs), msgs


def test_shape_contract_undeclared_fetch_role(tmp_path):
    """A device_get moving a leaf no FETCH_BUDGETS role declares is an
    undeclared transfer; declared roles (any order/subset) are fine."""
    src = """\
import jax

def timed_fetch(run):
    vals, idx = jax.device_get((vals_dev, topi))
    everything = jax.device_get((vals_dev, stacked_full))
    return vals, idx, everything
"""
    root = _fake_repo(tmp_path, ("rca_tpu/engine/runner.py", src))
    hits = _lint(root, ["shape-contract"]).findings
    assert len(hits) == 1 and "stacked_full" in hits[0].message, [
        (f.line, f.message) for f in hits
    ]


# -- dtype-discipline --------------------------------------------------------

def test_dtype_low_precision_cast_fires_outside_quantized(tmp_path):
    root = _fake_repo(
        tmp_path,
        ("rca_tpu/engine/foo.py",
         "import jax.numpy as jnp\n\ndef f(x):\n"
         "    return x.astype(jnp.bfloat16)\n"),
        ("rca_tpu/engine/quantized.py",
         "import jax.numpy as jnp\n\ndef q(x):\n"
         "    return x.astype(jnp.bfloat16)\n"),
    )
    hits = _lint(root, ["dtype-discipline"]).findings
    assert [f.path for f in hits] == ["rca_tpu/engine/foo.py"]


def test_dtype_int8_device_vs_host_metadata(tmp_path):
    """jnp-rooted int8 is kernel arithmetic (fires); np-rooted int8 in a
    host module is a compact metadata tag (legal — graph/build.py)."""
    root = _fake_repo(tmp_path, ("rca_tpu/graph/meta.py", """\
import numpy as np
import jax.numpy as jnp

def tag(x):
    host = np.asarray(x, dtype=np.int8)
    dev = jnp.asarray(x, dtype=jnp.int8)
    return host, dev
"""))
    hits = _lint(root, ["dtype-discipline"]).findings
    assert len(hits) == 1 and hits[0].line == 6, [
        (f.line, f.message) for f in hits
    ]


def test_dtype_float64_staging_in_dataplane(tmp_path):
    root = _fake_repo(
        tmp_path,
        ("rca_tpu/engine/streaming.py",
         "import numpy as np\nbuf = np.zeros((4, 4), np.float64)\n"),
        ("rca_tpu/tools_helper.py",
         "import numpy as np\nacc = np.zeros((4, 4), np.float64)\n"),
    )
    hits = _lint(root, ["dtype-discipline"]).findings
    assert [f.path for f in hits] == ["rca_tpu/engine/streaming.py"]
    assert "float64 staging" in hits[0].message


def test_dtype_implicit_promotion_in_jit_body(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/engine/foo.py", """\
import jax
import jax.numpy as jnp

@jax.jit
def mix(n):
    a = jnp.zeros((4,), jnp.bfloat16)
    b = jnp.ones((4,), jnp.float32)
    return a * b
"""))
    msgs = [f.message for f in _lint(root, ["dtype-discipline"]).findings]
    assert any("implicit" in m and "promotion" in m for m in msgs), msgs


# -- donation-guard ----------------------------------------------------------

_DONATE_HEADER = """\
from functools import partial
import jax

@partial(jax.jit, donate_argnums=(0,))
def step(buf, x):
    return buf + x
"""


def test_donation_read_after_donate_fires(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/engine/sess.py",
                                 _DONATE_HEADER + """\

class Sess:
    def tick(self, x):
        out = step(self._buf, x)
        return self._buf * 2
"""))
    hits = _lint(root, ["donation-guard"]).findings
    assert len(hits) == 1 and hits[0].line == 11, [
        (f.line, f.message) for f in hits
    ]
    assert "DELETED" in hits[0].message


def test_donation_same_statement_rebind_is_clean(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/engine/sess.py",
                                 _DONATE_HEADER + """\

class Sess:
    def tick(self, x):
        self._buf = step(self._buf, x)
        return self._buf * 2

    def tick_tuple(self, x):
        with self._mesh:
            self._buf, aux = unpack(step(self._buf, x))
        return self._buf * 2, aux
"""))
    assert _lint(root, ["donation-guard"]).findings == []


def test_donation_bound_jit_wrap_form(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/engine/sess.py", """\
import jax

def raw(buf, x):
    return buf + x

step = jax.jit(raw, donate_argnums=(0,))

def run(buf, x):
    out = step(buf, x)
    return buf
"""))
    hits = _lint(root, ["donation-guard"]).findings
    assert len(hits) == 1 and hits[0].line == 10


def test_donation_attr_callable_contract_table(tmp_path):
    """DONATED_ATTR_CALLABLES covers runtime-built jit wrappers bound to
    attributes — calls through self._fn in parallel/streaming.py donate
    argument 0 even though no decorator is visible."""
    root = _fake_repo(tmp_path, ("rca_tpu/parallel/streaming.py", """\
class ShardedStreamingSession:
    def flush(self, idx, rows):
        out = self._fn(self._features, idx, rows)
        return self._features
"""))
    hits = _lint(root, ["donation-guard"]).findings
    assert len(hits) == 1 and hits[0].line == 4


def test_donation_repo_sites_are_clean():
    """The four real donation sites all rebind in-statement."""
    result = run_lint(root=ROOT, rules=["donation-guard"],
                      use_baseline=False)
    assert result.findings == []


# -- the repo itself sweeps clean --------------------------------------------

def test_repo_sweeps_clean_on_all_graftspec_rules():
    """Acceptance criterion: the full repo passes shape-contract,
    dtype-discipline, and donation-guard with an EMPTY baseline."""
    result = run_lint(
        root=ROOT,
        rules=["shape-contract", "dtype-discipline", "donation-guard"],
        use_baseline=False,
    )
    assert result.findings == [], [
        (f.path, f.line, f.rule, f.message) for f in result.findings
    ]


# -- absint ------------------------------------------------------------------

def test_absint_unknown_is_honest():
    """Unmodeled constructs evaluate to UNKNOWN and conform to any
    declared role — a gap in the op table costs coverage, never a false
    positive."""
    import ast as ast_mod

    fn = ast_mod.parse("def f(x):\n    return mystery(x)\n").body[0]
    interp = absint.interpret_function(fn, {})
    assert interp.returns == [contracts.UNKNOWN]
    role = contracts.Role("vals", ("k",), "float32")
    assert absint.fact_conforms(contracts.UNKNOWN, role) is None


def test_absint_promote_and_broadcast():
    assert absint.promote("bfloat16", "float32") == "float32"
    assert absint.promote(None, "int32") == "int32"
    assert absint.broadcast((4, "k"), ("k",)) == (4, "k")
    assert absint.broadcast((1, "k"), (8, 1)) == (8, "k")


# -- specsan -----------------------------------------------------------------

_TOPK = (
    contracts.Role("vals", ("k",), "float32"),
    contracts.Role("idx", ("k",), "int32"),
    contracts.Role("n_bad", (), "int32"),
)


def test_unify_roles_binds_symbols_consistently():
    leaves = [((5,), "float32"), ((5,), "int32"), ((), "int32")]
    binding = unify_roles(leaves, _TOPK)
    assert binding == {"k": 5}


def test_unify_roles_rejects_inconsistent_dims_and_dtypes():
    assert unify_roles([((5,), "float32"), ((6,), "int32")], _TOPK) is None
    assert unify_roles([((5,), "float64")], _TOPK) is None


def test_recorder_judges_over_budget():
    rec = SpecsanRecorder(ROOT)
    budget = contracts.FetchBudget(_TOPK, "8*k + 8")
    event = {"surface": "rca_tpu/engine/streaming.py::fetch",
             "shapes": [[1024]], "dtypes": ["float32"], "nbytes": 4096}
    rec._judge(event, budget, [((1024,), "float32", 4096)], 4096)
    assert event["verdict"] == "ok"  # 4096 <= 8*1024 + 8

    event2 = {"surface": "rca_tpu/engine/streaming.py::fetch",
              "shapes": [[5], [5], [5]],
              "dtypes": ["float32", "float32", "float32"], "nbytes": 60}
    rec._judge(event2, budget,
               [((5,), "float32", 20)] * 3, 60)
    assert event2["verdict"] == "unmatched_roles"
    assert any(v["kind"] == "unmatched_roles" for v in rec.violations)


def test_confirm_findings_stamps_implicated_paths():
    findings = [
        {"rule": "shape-contract", "path": "rca_tpu/engine/runner.py"},
        {"rule": "shape-contract", "path": "rca_tpu/engine/other.py"},
        {"rule": "rng-key-reuse", "path": "rca_tpu/engine/runner.py"},
    ]
    report = {"violations": [
        {"kind": "over_budget",
         "surface": "rca_tpu/engine/runner.py::timed_fetch"},
    ]}
    assert confirm_findings(findings, report) == 1
    assert findings[0].get("dynamically_confirmed") is True
    assert "dynamically_confirmed" not in findings[1]
    assert "dynamically_confirmed" not in findings[2]


@pytest.mark.parametrize("fixture", [
    "chaos-20svc-seed11.rcz",
    "columnar-20svc-seed21.rcz",
])
def test_specsan_replay_property(fixture):
    """The specsan <-> static property on REAL recorded workloads: every
    device fetch a corpus replay performs must unify with the declared
    contract roles and fit the declared budgets — zero violations, and
    the replay must actually exercise at least one budgeted surface."""
    from rca_tpu.replay import replay

    path = os.path.join(CORPUS, fixture)
    with capture(ROOT) as rec:
        report = replay(path)
    assert report.get("ok", True) in (True, None) or report
    assert rec.violations == [], rec.violations
    budgeted = {f"{p}::{f}" for p, f in contracts.FETCH_BUDGETS}
    exercised = {e["surface"] for e in rec.events} & budgeted
    assert exercised, "replay exercised no budgeted fetch surface"
    assert all(
        e["verdict"] == "ok"
        for e in rec.events if e["surface"] in budgeted
    )


# -- satellites: atomic index + parse cache ----------------------------------

def test_update_index_atomic_crash_mid_write(tmp_path, monkeypatch):
    root = str(tmp_path)
    target = tmp_path / "a.py"
    target.write_text("x = 1\n")
    update_index(root, ["a.py"])
    before = load_index(root)
    assert "a.py" in before

    target.write_text("x = 2\n")
    real_dump = json.dump

    def exploding_dump(obj, fh, **kw):
        fh.write('{"version": 1, "files": {"a.py": "TORN')
        raise OSError("disk full mid-write")

    monkeypatch.setattr(json, "dump", exploding_dump)
    update_index(root, ["a.py"])  # must not raise, must not publish
    monkeypatch.setattr(json, "dump", real_dump)

    assert load_index(root) == before  # old index intact, not torn
    leftovers = [
        n for n in os.listdir(os.path.dirname(index_path(root)))
        if ".tmp." in n
    ]
    assert leftovers == []  # the partial temp file was cleaned up


def test_parse_cache_hits_and_invalidation(tmp_path):
    f = tmp_path / "m.py"
    f.write_text("a = 1\n")
    s0 = parse_cache_stats()
    src1, tree1 = parse_file(str(f))
    src2, tree2 = parse_file(str(f))
    s1 = parse_cache_stats()
    assert tree1 is tree2  # the SAME tree object: one parse
    assert s1["hits"] - s0["hits"] == 1
    assert s1["misses"] - s0["misses"] == 1

    f.write_text("a = 2\n")
    os.utime(str(f), ns=(1, 1))  # force a distinct (mtime, size) key
    src3, _ = parse_file(str(f))
    assert src3 == "a = 2\n"  # edit invalidates


def test_lint_result_reports_parse_cache(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/m.py", "x = 1\n"))
    result = run_lint(root=root, rules=["shape-contract"],
                      use_baseline=False)
    assert set(result.parse_cache) == {"hits", "misses"}
    assert "parse_cache" in result.to_dict()
