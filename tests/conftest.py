"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
imports anywhere, so sharding/collective tests run hermetically without TPU
hardware (the driver separately dry-run-compiles the multi-chip path)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A site hook may have force-registered an accelerator plugin before this
# conftest ran; config.update wins over it where the env var does not.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from rca_tpu.cluster.fixtures import five_service_world  # noqa: E402
from rca_tpu.cluster.generator import synthetic_cascade_world  # noqa: E402
from rca_tpu.cluster.mock_client import MockClusterClient  # noqa: E402


def import_setup_tool():
    """Import tools/setup_test_cluster.py (not a package; path-local).
    Remove the EXACT entry afterwards — the tool itself appends the repo
    root to sys.path at import, so a blind pop(0) could strip the wrong
    path."""
    import sys as _sys

    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools",
    )
    _sys.path.insert(0, tools)
    try:
        import setup_test_cluster as stc
    finally:
        _sys.path.remove(tools)
    return stc


@pytest.fixture()
def five_svc_client() -> MockClusterClient:
    return MockClusterClient(five_service_world())


@pytest.fixture(scope="session")
def fifty_svc_client() -> MockClusterClient:
    return MockClusterClient(
        synthetic_cascade_world(50, n_roots=1, seed=7, namespace="synthetic")
    )
