"""ISSUE 13: the grown kernel set behind the one dispatch seam.

- quantized (bf16 + per-row int8 messages): RANK-parity property tests
  vs the f32 path over random update/delete/NaN sequences at several
  shapes — hit@1/hit@3 equality + a Kendall-tau floor, the kernel's
  landing gate (bit parity would make it unlandable by construction);
- doubling (log-depth operator doubling): the up-scan is BIT-identical
  to the serial 8-step chain (fp32 max is order-invariant and the
  decay multiplies replay the serial sequence — engine/doubling.py),
  the down-scan is tight-allclose, rankings identical; plus the
  frontier-cap decline path;
- the corpus replay leg: every committed fixture replays under
  ``RCA_KERNEL=quantized`` with tick-by-tick rank parity;
- the 60-tick depth-2 chaos soak stays green (zero post-warmup
  recompiles, memory gate ok) under each forced kernel;
- every surface stamps the engaged kernel (streaming session, serve
  dispatcher, resident session);
- ``rca kernels --explain`` and the bench_guard winner-flip gate.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np
import pytest

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.engine.quantized import (
    kendall_tau,
    rank_parity,
    topk_score_tau,
)
from rca_tpu.engine.registry import KERNELS, reset_registry


@pytest.fixture(autouse=True)
def _fresh_registry(monkeypatch):
    monkeypatch.setenv("RCA_KERNEL_CACHE", "0")
    reset_registry()
    yield
    reset_registry()


def _engine(monkeypatch, kernel=None):
    from rca_tpu.engine.runner import GraphEngine

    if kernel is None:
        monkeypatch.delenv("RCA_KERNEL", raising=False)
    else:
        monkeypatch.setenv("RCA_KERNEL", kernel)
    return GraphEngine()


# ---------------------------------------------------------------------------
# rank-parity gate helpers
# ---------------------------------------------------------------------------

def test_kendall_tau_and_rank_parity_semantics():
    assert kendall_tau(["a", "b", "c"], ["a", "b", "c"]) == 1.0
    assert kendall_tau(["a", "b", "c"], ["c", "b", "a"]) == -1.0
    assert kendall_tau(["a"], ["a"]) == 1.0
    ref = [{"component": x} for x in "abcde"]
    assert rank_parity(ref, ref)["ok"]
    swapped_tail = [{"component": x} for x in "abced"]
    rep = rank_parity(ref, swapped_tail)
    assert rep["hit1_equal"] and rep["hit3_equal"]
    assert rep["kendall_tau"] < 1.0
    flipped_top = [{"component": x} for x in "bacde"]
    assert not rank_parity(ref, flipped_top)["ok"]


def test_quantize_roundtrip_accuracy():
    import jax.numpy as jnp

    from rca_tpu.engine.quantized import dequant_gather, quantize_rows

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 3.0, 1024).astype(np.float32))
    q, scale = quantize_rows(x)
    idx = jnp.asarray(rng.integers(0, 1024, 4096).astype(np.int32))
    got = np.asarray(dequant_gather(q, scale, idx))
    want = np.asarray(x)[np.asarray(idx)]
    # symmetric per-row int8: error bounded by half a step of the row max
    assert np.abs(got - want).max() <= float(np.max(x)) / 127.0
    # all-zero rows dequantize to exact zero (no 0/0)
    q0, s0 = quantize_rows(jnp.zeros(256))
    assert np.asarray(dequant_gather(q0, s0, jnp.arange(256))).max() == 0.0


# ---------------------------------------------------------------------------
# quantized: rank-parity property tests vs f32
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [48, 200, 600])
def test_quantized_rank_parity_over_update_delete_nan_sequences(
        monkeypatch, n):
    """The quantized kernel's landing gate, as a property test: random
    update/delete/NaN mutation sequences over several shapes must keep
    hit@1/hit@3 and a Kendall-tau >= 0.99 vs the f32 path on every
    analyze."""
    c = synthetic_cascade_arrays(n, n_roots=2, seed=11)
    root_names = {c.names[i] for i in c.roots.tolist()}
    f32 = _engine(monkeypatch)
    f32_first = f32.analyze_case(c, k=5)
    quant = _engine(monkeypatch, "quantized")
    rng = np.random.default_rng(5)
    feats = c.features.copy()
    taus = []
    for step in range(6):
        q_res = quant.analyze_arrays(feats, c.dep_src, c.dep_dst,
                                     c.names, k=5)
        monkeypatch.delenv("RCA_KERNEL")
        f_res = f32.analyze_arrays(feats, c.dep_src, c.dep_dst,
                                   c.names, k=5)
        monkeypatch.setenv("RCA_KERNEL", "quantized")
        rep = rank_parity(f_res.ranked, q_res.ranked)
        # the gate the kernel lands under: identical leader, identical
        # hit@1/hit@3 vs the ROOTS, tau floor on the top-k order (a
        # sub-1e-3 near-tie in the non-root tail may legitimately swap)
        assert rep["hit1_equal"], (n, step, rep)
        f_top = f_res.top_components()
        q_top = q_res.top_components()
        assert ((f_top[0] in root_names) == (q_top[0] in root_names))
        assert (bool(root_names & set(f_top[:3]))
                == bool(root_names & set(q_top[:3])))
        # tie-aware tau over the top-25: pairs the f32 path separates
        # by more than the int8 step must keep their order (sub-2e-3
        # background near-ties carry no rank signal — quantized.py)
        taus.append(topk_score_tau(f_res.score, q_res.score))
        assert q_res.sanitized_rows == f_res.sanitized_rows
        # mutate: a few row updates, one delete (zero), one NaN poison
        for i in rng.integers(0, n, 4):
            feats[i] = np.clip(
                feats[i] + rng.uniform(-0.3, 0.3, feats.shape[1]), 0, 1
            ).astype(np.float32)
        feats[int(rng.integers(0, n))] = 0.0
        feats[int(rng.integers(0, n)), 0] = np.nan
    assert min(taus) >= 0.99, taus
    # and the f32 engine was untouched by the forced env (plans pin at
    # session creation): same first answer now as before
    monkeypatch.delenv("RCA_KERNEL")
    assert (f32.analyze_case(c, k=5).top_components()
            == f32_first.top_components())


def test_quantized_streaming_session_rank_parity(monkeypatch):
    from rca_tpu.engine.streaming import StreamingSession

    c = synthetic_cascade_arrays(300, n_roots=2, seed=9)
    names = [f"s{i}" for i in range(c.n)]

    def run(kernel):
        if kernel:
            monkeypatch.setenv("RCA_KERNEL", kernel)
        else:
            monkeypatch.delenv("RCA_KERNEL", raising=False)
        reset_registry()
        sess = StreamingSession(
            names, c.dep_src, c.dep_dst, c.features.shape[1], k=5
        )
        assert sess.kernel_path == (kernel or "xla")
        sess.set_all(c.features)
        outs = [sess.tick()]
        sess.update(3, np.clip(c.features[3] + 0.5, 0, 1))
        outs.append(sess.tick())
        outs.append(sess.tick())  # quiet tick
        return [o["ranked"] for o in outs]

    base = run(None)
    quant = run("quantized")
    for b, q in zip(base, quant):
        assert rank_parity(b, q)["ok"]


# ---------------------------------------------------------------------------
# doubling: bit-parity with the serial chain (interpret-mode/CPU host)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,steps,decay", [
    (180, 8, 0.7), (700, 8, 0.7), (120, 4, 0.7), (90, 2, 0.7),
])
def test_doubling_parity_vs_serial_chain(n, steps, decay):
    """Up-scan BIT-identical for any decay (order-invariant max, serial
    multiply sequence); down-scan tight-allclose (sums reassociate, the
    same class as the shipped segscan layout); identical ranking."""
    import jax.numpy as jnp

    from rca_tpu.config import RCAConfig, bucket_for
    from rca_tpu.engine.doubling import build_doubling
    from rca_tpu.engine.propagate import (
        _noisy_or,
        default_params,
        propagate_core,
    )

    c = synthetic_cascade_arrays(n, n_roots=2, seed=3)
    buckets = RCAConfig().shape_buckets
    n_pad = bucket_for(n + 1, buckets)
    e_pad = bucket_for(len(c.dep_src), buckets)
    dummy = n_pad - 1
    s = np.full(e_pad, dummy, np.int32)
    d = np.full(e_pad, dummy, np.int32)
    s[: len(c.dep_src)] = c.dep_src
    d[: len(c.dep_dst)] = c.dep_dst
    aw, hw = default_params().weight_arrays()
    f = np.zeros((n_pad, c.features.shape[1]), np.float32)
    f[:n] = c.features
    a = _noisy_or(jnp.asarray(f), aw)
    h = _noisy_or(jnp.asarray(f), hw)
    args = (a, h, jnp.asarray(s), jnp.asarray(d), steps, decay, 0.85, 1.6)
    ref = propagate_core(*args)
    dbl = build_doubling(n_pad, e_pad, c.dep_src, c.dep_dst, steps)
    assert dbl is not None
    got = propagate_core(*args, dbl=dbl)
    # upstream: BITWISE
    assert np.array_equal(np.asarray(ref[2]), np.asarray(got[2])), (
        "doubled up-scan must be bit-identical to the serial chain"
    )
    # impact + score: tight allclose, identical top-k order
    np.testing.assert_allclose(np.asarray(got[3]), np.asarray(ref[3]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got[4]), np.asarray(ref[4]),
                               rtol=1e-5, atol=1e-6)
    assert (np.argsort(-np.asarray(got[4]))[:5].tolist()
            == np.argsort(-np.asarray(ref[4]))[:5].tolist())


def test_doubling_engine_end_to_end(monkeypatch):
    c = synthetic_cascade_arrays(400, n_roots=3, seed=17)
    base = _engine(monkeypatch).analyze_case(c, k=5)
    dbl = _engine(monkeypatch, "doubling").analyze_case(c, k=5)
    np.testing.assert_allclose(dbl.score, base.score, rtol=1e-5, atol=1e-6)
    assert dbl.top_components() == base.top_components()


def test_doubling_declines_non_power_of_two_depth():
    from rca_tpu.engine.doubling import build_doubling, doubling_eligible

    assert doubling_eligible(8) and doubling_eligible(2)
    assert not doubling_eligible(6) and not doubling_eligible(1)
    c = synthetic_cascade_arrays(60, n_roots=1, seed=0)
    assert build_doubling(64, 128, c.dep_src, c.dep_dst, 6) is None


def test_doubling_frontier_cap_falls_back_to_serial(monkeypatch):
    """A hub-heavy graph whose squared frontier blows the cap must fall
    back to the serial path — and the PLAN (what actually ran) says so,
    not the shape row."""
    import rca_tpu.engine.doubling as dbl_mod
    from rca_tpu.engine.runner import kernel_plan

    monkeypatch.setenv("RCA_KERNEL", "doubling")
    monkeypatch.setattr(dbl_mod, "MAX_FRONTIER_MULT", 0)
    dbl_mod._DOUBLING_CACHE.clear()
    c = synthetic_cascade_arrays(100, n_roots=1, seed=1)
    plan = kernel_plan(128, 256, c.dep_src, c.dep_dst, steps=8)
    assert plan.kernel == "xla" and plan.dbl is None
    dbl_mod._DOUBLING_CACHE.clear()
    # engine still answers correctly through the fallback
    res = _engine(monkeypatch, "doubling").analyze_case(c, k=3)
    assert res.ranked
    dbl_mod._DOUBLING_CACHE.clear()


# ---------------------------------------------------------------------------
# every surface stamps the engaged kernel
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kernel", ["quantized", "doubling"])
def test_serve_dispatcher_stamps_and_serves_kernel(monkeypatch, kernel):
    from rca_tpu.serve.dispatcher import BatchDispatcher
    from rca_tpu.serve.request import ServeRequest

    monkeypatch.setenv("RCA_KERNEL", kernel)
    c = synthetic_cascade_arrays(80, n_roots=1, seed=4)
    disp = BatchDispatcher(engine=_engine(monkeypatch, kernel))
    reqs = [
        ServeRequest(tenant="t", features=c.features, dep_src=c.dep_src,
                     dep_dst=c.dep_dst, names=c.names, k=3)
        for _ in range(3)
    ]
    handle = disp.dispatch(reqs)
    assert handle.kernel == kernel
    results = disp.fetch(handle)
    assert len(results) == 3
    solo = results[0]
    assert solo.ranked
    # any-width == solo parity holds under the forced kernel too
    solo_handle = disp.dispatch([reqs[0]])
    solo_res = disp.fetch(solo_handle)[0]
    assert [r["component"] for r in solo_res.ranked] == \
        [r["component"] for r in results[0].ranked]


def test_resident_session_serves_forced_kernel(monkeypatch):
    c = synthetic_cascade_arrays(150, n_roots=2, seed=6)
    eng = _engine(monkeypatch, "quantized")
    assert eng._resident_cache is not None
    first = eng.analyze_case(c, k=5)
    # delta request through the pinned quantized session
    feats = c.features.copy()
    feats[7] = np.clip(feats[7] + 0.4, 0, 1)
    again = eng.analyze_arrays(feats, c.dep_src, c.dep_dst, c.names, k=5)
    assert again.ranked
    sess = next(iter(eng._resident_cache._sessions.values()))
    assert sess._plan.kernel == "quantized"
    assert sess.delta_requests >= 1
    assert first.ranked


# ---------------------------------------------------------------------------
# corpus replay leg: rank parity tick-by-tick under RCA_KERNEL=quantized
# ---------------------------------------------------------------------------

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus")
FIXTURES = sorted(glob.glob(os.path.join(CORPUS_DIR, "*.rcz")))


@pytest.mark.parametrize("path", FIXTURES,
                         ids=[os.path.basename(p) for p in FIXTURES])
def test_corpus_replays_rank_parity_under_quantized(monkeypatch, path):
    """ISSUE 13 satellite: the committed corpus replays under the
    quantized kernel with RANK parity tick-by-tick (the recordings are
    f32 evidence — a bitwise gate would be vacuous-fail; the ranking
    gate is the claim the kernel actually makes)."""
    from rca_tpu.replay import load_recording, replay

    if load_recording(path).mode == "serve":
        pytest.skip("rank-parity leg targets stream recordings")
    monkeypatch.setenv("RCA_KERNEL", "quantized")
    report = replay(path, parity="rank")
    assert report["parity_mode"] == "rank"
    assert report["parity_ok"], {
        k: report.get(k)
        for k in ("first_divergent_tick", "mismatched_ticks",
                  "unconsumed_calls")
    }


# ---------------------------------------------------------------------------
# chaos soak under each forced kernel (ISSUE 13 acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kernel", ["segscan", "quantized", "doubling"])
def test_chaos_soak_green_under_each_forced_kernel(monkeypatch, kernel):
    """The 60-tick depth-2 chaos soak with kernelscope's
    zero-post-warmup-recompile and memory-leak gates must stay green
    under each forced kernel (segscan runs interpreted off-TPU)."""
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.resilience.chaos import ChaosConfig, run_chaos_soak

    monkeypatch.setenv("RCA_KERNEL", kernel)
    summary = run_chaos_soak(
        lambda: synthetic_cascade_world(14, n_roots=1, seed=11),
        "synthetic", seed=11, ticks=60, k=5,
        config=ChaosConfig(seed=11), pipeline_depth=2,
    )
    assert summary["uncaught_exceptions"] == 0
    # the auto-selected gate mode (rank for quantized — ISSUE 13);
    # parity_ok itself is asserted by the depth-1 soak below, matching
    # the depth-2 posture of the pre-existing ISSUE 12 soak test
    assert summary["parity_mode"] == (
        "rank" if kernel == "quantized" else "exact"
    )
    scope = summary["kernelscope"]
    assert scope["enabled"]
    assert scope["recompiles_post_warm"] == 0, scope
    assert scope["memory_gate"]["ok"], scope["memory_gate"]


def test_chaos_soak_parity_holds_per_kernel(monkeypatch):
    """Depth-1 soak: the fault-free parity gate itself holds under each
    forced kernel (rank mode engages for quantized)."""
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.resilience.chaos import ChaosConfig, run_chaos_soak

    for kernel in ("segscan", "quantized", "doubling"):
        monkeypatch.setenv("RCA_KERNEL", kernel)
        reset_registry()
        summary = run_chaos_soak(
            lambda: synthetic_cascade_world(14, n_roots=1, seed=11),
            "synthetic", seed=11, ticks=24, k=5,
            config=ChaosConfig(seed=11),
        )
        assert summary["uncaught_exceptions"] == 0
        assert summary["parity_ok"], (kernel, summary)
        assert summary["parity_ticks_checked"] > 0


# ---------------------------------------------------------------------------
# rca kernels --explain
# ---------------------------------------------------------------------------

def test_kernels_cli_explain_lists_full_candidate_set(monkeypatch, capsys):
    from rca_tpu.cli import main as cli_main

    monkeypatch.setenv("RCA_KERNEL", "quantized")
    rc = cli_main(["kernels", "--services", "300", "--edges", "700",
                   "--no-cost", "--explain"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "winner=quantized (forced)" in out
    for k in KERNELS:
        assert k in out
    # a declined candidate names its gate or its race outcome
    assert "ineligible:" in out or "not raced" in out


def test_kernels_cli_json_rows_carry_eligibility(monkeypatch, capsys):
    from rca_tpu.cli import main as cli_main

    rc = cli_main(["kernels", "--services", "300", "--edges", "700",
                   "--json", "--compact", "--no-cost"])
    assert rc == 0
    rows = json.loads(capsys.readouterr().out)["rows"]
    row = next(r for r in rows if r["variant"] == "dense")
    assert row["e_pad"] is not None
    for k in ("segscan", "quantized", "doubling"):
        assert k in row["eligible"]


# ---------------------------------------------------------------------------
# bench_guard: kernel winner-flip gate
# ---------------------------------------------------------------------------

def _guard_mod():
    import importlib
    import sys

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        return importlib.import_module("bench_guard")
    finally:
        sys.path.remove(tools)


def _line(winner, timings, source="timed"):
    return {
        "tick_ms_10k": 10.0,
        "kernel_registry": [{
            "variant": "dense", "n_pad": 2048, "e_pad": 8192,
            "backend": "tpu", "winner": winner, "source": source,
            "timings_ms": timings,
        }],
    }


def test_kernel_guard_fails_unjustified_winner_flip():
    bg = _guard_mod()
    base = _line("segscan", {"xla": 1.0, "segscan": 0.7})
    # flip back to xla with no >10% win recorded: autotune noise
    cur = _line("xla", {"xla": 0.68, "segscan": 0.7})
    report = bg.compare(cur, base)
    assert not report["ok"]
    flip = report["kernel_table"]["flips"][0]
    assert flip["status"] == "unjustified-flip"
    assert (flip["winner_was"], flip["winner_now"]) == ("segscan", "xla")


def test_kernel_guard_accepts_justified_flip_and_skips_forced():
    bg = _guard_mod()
    base = _line("xla", {"xla": 1.0, "quantized": 1.1})
    cur = _line("quantized", {"xla": 1.0, "quantized": 0.6})
    assert bg.compare(cur, base)["ok"]          # >10% win: justified
    # forced rows flip legitimately with the env: not compared
    report = bg.compare(_line("doubling", {}, source="forced"), base)
    assert report["ok"]
    # identical winners: nothing to flag
    assert bg.compare(base, base)["ok"]
