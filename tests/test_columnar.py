"""ISSUE 10: columnar world state — vectorized capture + column-diff
replay.

The load-bearing contract is BIT-parity: a columnar capture+extraction
must produce a :class:`FeatureSet` byte-identical to the per-object dict
path's over the same world — asserted directly, under a randomized
update/delete/NaN/gone-storm property, through live sessions at pipeline
depth 1 and 2, and across the record/replay boundary (coldiff frames).
Backward compatibility rides the corpus: the pre-columnar ``.rcz``
fixture must keep replaying through the dict path.
"""

from __future__ import annotations

import copy
import os

import numpy as np
import pytest

from rca_tpu.cluster.columnar import ColumnarClientState
from rca_tpu.cluster.fixtures import NS, five_service_world
from rca_tpu.cluster.generator import synthetic_cascade_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.cluster.world import (
    make_deployment,
    make_event,
    make_pod,
    make_service,
    waiting_status,
)
from rca_tpu.engine.live import LiveStreamingSession
from rca_tpu.engine.runner import GraphEngine
from rca_tpu.features.extract import extract_features

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _featureset_bits(fs):
    return (
        fs.pod_names, fs.service_names, fs.node_names,
        fs.pod_features.tobytes(), fs.service_features.tobytes(),
        fs.pod_service.tobytes(), fs.memb_pod.tobytes(),
        fs.memb_svc.tobytes(), fs.pod_node.tobytes(),
        fs.node_features.tobytes(),
    )


def _assert_bit_parity(client, ns, columnar_state=None, ctx=""):
    """Columnar capture+extract == dict capture+extract, bitwise."""
    snap_c = ClusterSnapshot.capture(
        client, ns, columnar_state=columnar_state,
    )
    assert snap_c.columnar is not None, f"{ctx}: columnar path not taken"
    snap_d = ClusterSnapshot.capture(client, ns, columnar=False)
    fs_c = extract_features(snap_c)
    fs_d = extract_features(snap_d)
    assert _featureset_bits(fs_c) == _featureset_bits(fs_d), (
        f"{ctx}: columnar FeatureSet diverged from dict path"
    )
    # the snapshot's object lists are order-identical too (consumers
    # downstream of the extractor index into them)
    assert snap_c.pods == snap_d.pods
    assert snap_c.services == snap_d.services
    assert snap_c.events == snap_d.events
    assert snap_c.logs == snap_d.logs
    return fs_c


# -- direct capture parity ---------------------------------------------------

def test_capture_parity_cascade_world():
    world = synthetic_cascade_world(120, n_roots=2, seed=3, namespace="ns")
    _assert_bit_parity(MockClusterClient(world), "ns")


def test_capture_parity_five_service_fixture():
    _assert_bit_parity(MockClusterClient(five_service_world()), NS)


def test_capture_parity_property_update_delete_nan_gone(monkeypatch):
    """THE property gate: after any journaled sequence of pod
    replacements, deletions, additions, NaN-poisoned metrics, log
    rewrites, event storms, service adds, and journal-trim gone storms,
    the columnar tables (maintained incrementally through one shared
    cursor state) still extract bit-identically to a fresh dict sweep."""
    ns = "prop"
    world = synthetic_cascade_world(25, n_roots=2, seed=9, namespace=ns)
    client = MockClusterClient(world)
    state = ColumnarClientState()
    rng = np.random.default_rng(42)

    def mutate(step: int) -> None:
        op = int(rng.integers(0, 8))
        pods = world.pods[ns]
        if op == 0:      # status flip (replacement + touch)
            idx = int(rng.integers(0, len(pods)))
            pod = copy.deepcopy(pods[idx])
            app = pod["metadata"]["labels"].get("app", "x")
            if rng.random() < 0.5:
                pod["status"]["phase"] = "Running"
                pod["status"]["containerStatuses"] = [waiting_status(
                    app, "CrashLoopBackOff",
                    restarts=int(rng.integers(1, 9)), last_exit_code=1,
                )]
            else:
                pod["status"]["phase"] = "Pending"
                pod["status"]["containerStatuses"] = []
            pods[idx] = pod
            world.touch("pod", ns, pod["metadata"]["name"])
        elif op == 1:    # NaN-poisoned metrics (the sanitizer's food)
            recs = world.pod_metrics[ns]["pods"]
            name = list(recs)[int(rng.integers(0, len(recs)))]
            rec = copy.deepcopy(recs[name])
            rec["cpu"]["usage_percentage"] = float("nan")
            rec["memory"]["usage_percentage"] = float(
                rng.uniform(5, 99)
            )
            recs[name] = rec
            world.touch("pod_metrics", ns, name)
        elif op == 2:    # log rewrite
            logs = world.logs[ns]
            name = list(logs)[int(rng.integers(0, len(logs)))]
            cont = next(iter(logs[name]))
            logs[name][cont] = (
                "ERROR: connection refused\n" * int(rng.integers(1, 4))
            )
            world.touch("logs", ns, name)
        elif op == 3:    # pod deletion
            if len(pods) > 12:
                idx = int(rng.integers(0, len(pods)))
                pod = pods.pop(idx)
                world.touch("pod", ns, pod["metadata"]["name"])
        elif op == 4:    # pod addition (delete-then-readd ordering too)
            name = f"late-{step}"
            world.add("pods", ns, make_pod(name, ns, "late"))
        elif op == 5:    # warning-event storm for one pod
            victim = pods[int(rng.integers(0, len(pods)))]
            world.add("events", ns, make_event(
                ns, "Pod", victim["metadata"]["name"], "BackOff",
                "storm", count=int(rng.integers(1, 9)),
            ))
        elif op == 6:    # topology move
            svc = f"newsvc-{step}"
            world.add("services", ns, make_service(svc, ns))
            world.add("deployments", ns, make_deployment(svc, ns, svc))
        else:            # gone storm: trim the journal past every cursor
            old_cap = world.journal_cap
            world.journal_cap = 2
            for i in range(5):
                world.touch("pod", ns, f"ghost-{step}-{i}")
            world.journal_cap = old_cap

    for step in range(24):
        for _ in range(int(rng.integers(1, 4))):
            mutate(step)
        _assert_bit_parity(client, ns, columnar_state=state,
                           ctx=f"step {step}")


# -- live session parity -----------------------------------------------------

def _mutation_driver(world, ns, rng):
    def mutate(step: int) -> None:
        op = int(rng.integers(0, 5))
        pods = world.pods[ns]
        if op == 0:
            idx = int(rng.integers(0, len(pods)))
            pod = copy.deepcopy(pods[idx])
            pod["status"]["phase"] = (
                "Pending" if rng.random() < 0.5 else "Running"
            )
            pods[idx] = pod
            world.touch("pod", ns, pod["metadata"]["name"])
        elif op == 1:
            recs = world.pod_metrics[ns]["pods"]
            name = list(recs)[int(rng.integers(0, len(recs)))]
            rec = copy.deepcopy(recs[name])
            rec["cpu"]["usage_percentage"] = float(rng.uniform(5, 99))
            recs[name] = rec
            world.touch("pod_metrics", ns, name)
        elif op == 2:
            logs = world.logs[ns]
            name = list(logs)[int(rng.integers(0, len(logs)))]
            cont = next(iter(logs[name]))
            logs[name][cont] = "ERROR: timeout\n" * int(
                rng.integers(1, 3)
            )
            world.touch("logs", ns, name)
        elif op == 3:
            if len(pods) > 10:
                idx = int(rng.integers(0, len(pods)))
                pod = pods.pop(idx)
                world.touch("pod", ns, pod["metadata"]["name"])
        else:
            svc = f"newsvc-{step}"
            world.add("services", ns, make_service(svc, ns))
            world.add("deployments", ns, make_deployment(svc, ns, svc))
    return mutate


@pytest.mark.parametrize("depth", [1, 2])
def test_live_session_columnar_vs_dict_parity(depth):
    """Two live sessions over one mutating world — columnar capture vs
    the dict patch path — deliver identical rankings at every poll, at
    pipeline depth 1 and 2.  (The world stays under the 25-healthy-pod
    log sampling cap, where the patch path is exactly fresh-capture
    equivalent — the documented boundary.)"""
    ns = "live"
    world = synthetic_cascade_world(20, n_roots=2, seed=5, namespace=ns)
    client = MockClusterClient(world)
    eng = GraphEngine()
    s_col = LiveStreamingSession(
        client, ns, k=5, topology_check_every=4, engine=eng,
        pipeline_depth=depth, use_columnar=True,
    )
    s_dict = LiveStreamingSession(
        client, ns, k=5, topology_check_every=4, engine=eng,
        pipeline_depth=depth, use_columnar=False,
    )
    rng = np.random.default_rng(0)
    mutate = _mutation_driver(world, ns, rng)
    for step in range(16):
        for _ in range(int(rng.integers(1, 4))):
            mutate(step)
        a = s_col.poll()
        b = s_dict.poll()
        assert [
            (r["component"], r["score"]) for r in a["ranked"]
        ] == [
            (r["component"], r["score"]) for r in b["ranked"]
        ], f"step {step} (depth {depth})"


def test_gone_storm_resets_mirror_and_recovers():
    """A journal trim expires BOTH feeds; the next poll resyncs off a
    full columnar payload and the rankings equal a fresh session's."""
    ns = "storm"
    world = synthetic_cascade_world(18, n_roots=1, seed=6, namespace=ns)
    client = MockClusterClient(world)
    eng = GraphEngine()
    live = LiveStreamingSession(
        client, ns, k=5, topology_check_every=10_000, engine=eng,
        use_columnar=True,
    )
    live.poll()
    old_cap = world.journal_cap
    world.journal_cap = 2
    for i in range(6):
        world.touch("pod", ns, f"ghost-{i}")
    world.journal_cap = old_cap
    out = live.poll()     # expiry recovery (graceful or resync)
    out2 = live.poll()    # settled
    fresh = LiveStreamingSession(
        client, ns, k=5, topology_check_every=10_000, engine=eng,
        use_columnar=True,
    )
    want = fresh.poll()
    assert [r["component"] for r in out2["ranked"]] == [
        r["component"] for r in want["ranked"]
    ]
    assert not out2.get("degraded")
    assert out is not None


def test_degenerate_world_falls_back_to_dict_path():
    """Duplicate object names make name-keyed maintenance unsound: the
    payload reports unsupported, capture falls back, and the session
    stays correct on the dict path."""
    ns = "dup"
    world = synthetic_cascade_world(8, n_roots=1, seed=2, namespace=ns)
    dup = copy.deepcopy(world.pods[ns][0])
    world.pods[ns].append(dup)  # same name twice
    client = MockClusterClient(world)
    payload = client.get_columnar(ns)
    assert payload["supported"] is False
    snap = ClusterSnapshot.capture(client, ns)
    assert snap.columnar is None  # dict path answered
    live = LiveStreamingSession(
        client, ns, k=3, topology_check_every=5, use_columnar=True,
    )
    out = live.poll()
    assert out["ranked"]
    assert live._use_columnar is False  # fallback is sticky


def test_columnar_capture_fault_degrades_then_recovers():
    """The columnar feed failing mid-session rides the existing
    resilience contract: poll() never raises, the ranking degrades to
    last-known, and the scheduled resync recovers once the feed heals."""
    ns = "flaky"
    world = synthetic_cascade_world(10, n_roots=1, seed=4, namespace=ns)

    class FlakyColumnar(MockClusterClient):
        broken = False

        def get_columnar(self, namespace, cursor=None):
            if self.broken:
                raise RuntimeError("columnar feed unreachable")
            return super().get_columnar(namespace, cursor)

    client = FlakyColumnar(world)
    live = LiveStreamingSession(
        client, ns, k=3, topology_check_every=10_000, engine=GraphEngine(),
        use_columnar=True,
    )
    healthy = live.poll()
    assert healthy["degraded"] is False
    client.broken = True
    live._pending_resync = True   # force a capture next poll
    out = live.poll()
    assert out["degraded"] is True
    assert out["ranked"] == healthy["ranked"]   # stale but served
    client.broken = False
    out2 = live.poll()
    assert out2["resynced"] is True
    assert out2["degraded"] is False
    assert [r["component"] for r in out2["ranked"]] == [
        r["component"] for r in healthy["ranked"]
    ]


def test_chaos_wrapper_does_not_advertise_columnar():
    """Chaos injection targets the dict getter surfaces; the wrapper
    therefore hides get_columnar so chaos soaks keep exercising the
    paths the seeded schedule perturbs."""
    from rca_tpu.resilience.chaos import ChaosClusterClient

    world = five_service_world()
    chaos = ChaosClusterClient(MockClusterClient(world))
    assert not hasattr(chaos, "get_columnar")
    snap = ClusterSnapshot.capture(chaos, NS)
    assert snap.columnar is None


# -- record/replay: column-diff frames ---------------------------------------

def _run_recorded_session(tmp_path, tag: str, use_columnar: bool) -> str:
    from rca_tpu.replay.recorder import Recorder

    ns = "rec"
    world = synthetic_cascade_world(18, n_roots=2, seed=11, namespace=ns)
    client = MockClusterClient(world)
    path = str(tmp_path / f"rec-{tag}")
    rec = Recorder(path)
    live = LiveStreamingSession(
        client, ns, k=5, topology_check_every=5, engine=GraphEngine(),
        recorder=rec, use_columnar=use_columnar,
    )
    rng = np.random.default_rng(7)
    mutate = _mutation_driver(world, ns, rng)
    for step in range(14):
        if step % 2 == 0:
            mutate(step)
        live.poll()
    rec.close()
    return path


def test_coldiff_recording_replays_bit_identical(tmp_path):
    from rca_tpu.replay.replayer import load_recording, replay_stream

    path = _run_recorded_session(tmp_path, "col", use_columnar=True)
    rec = load_recording(path)
    kinds = {fr.get("kind") for fr in rec.calls}
    assert "coldiff" in kinds, "columnar session must log coldiff frames"
    # per-tick digests are the one-pass CRC now
    assert all(
        fr.get("digest_algo") == "crc32"
        for fr in rec.ticks.values() if "features_digest" in fr
    )
    report = replay_stream(path)
    assert report["parity_ok"], report
    assert report["ticks_replayed"] == 14


def test_coldiff_recording_smaller_than_dict_recording(tmp_path):
    """Same world, same mutation schedule: the column-diff recording is
    substantially smaller than the dict-path one (which re-records whole
    object lists / event dumps per busy tick)."""
    p_col = _run_recorded_session(tmp_path, "c", use_columnar=True)
    p_dict = _run_recorded_session(tmp_path, "d", use_columnar=False)

    def tree_bytes(p):
        return sum(
            os.path.getsize(os.path.join(p, f)) for f in os.listdir(p)
        )

    b_col, b_dict = tree_bytes(p_col), tree_bytes(p_dict)
    assert b_col < b_dict, (b_col, b_dict)

    # and both replay clean through their own recorded path
    from rca_tpu.replay.replayer import replay_stream

    assert replay_stream(p_col)["parity_ok"]
    assert replay_stream(p_dict)["parity_ok"]


def test_precolumnar_fixture_still_replays_dict_path():
    """Backward-compat leg: the committed pre-columnar corpus fixture
    carries no coldiff frames, so its ReplaySource never advertises
    get_columnar and the replayed session runs the dict capture path —
    bit parity must hold exactly as it did before ISSUE 10."""
    from rca_tpu.replay.replayer import load_recording, replay_stream

    fixture = os.path.join(
        REPO_ROOT, "tests", "corpus", "chaos-20svc-seed11.rcz"
    )
    rec = load_recording(fixture)
    assert all(fr.get("kind") != "coldiff" for fr in rec.calls)
    # sha1-era digests are recognized as such
    assert all(
        fr.get("digest_algo") is None for fr in rec.ticks.values()
    )
    report = replay_stream(fixture)
    assert report["parity_ok"], report


# -- world index + table internals -------------------------------------------

def test_world_find_handles_replace_delete_and_shift():
    ns = "idx"
    world = synthetic_cascade_world(6, n_roots=1, seed=1, namespace=ns)
    pods = world.pods[ns]
    name3 = pods[3]["metadata"]["name"]
    assert world.find("pods", ns, name3) is pods[3]
    # in-place replacement at the same position
    clone = copy.deepcopy(pods[3])
    pods[3] = clone
    assert world.find("pods", ns, name3) is clone
    # deletion shifts positions: the verified index rebuilds
    gone = pods.pop(0)
    assert world.find("pods", ns, gone["metadata"]["name"]) is None
    assert world.find("pods", ns, name3) is clone
    # touch stamps the resourceVersion through the index
    seq_before = world.journal_seq
    world.touch("pod", ns, name3)
    assert clone["metadata"]["resourceVersion"] == str(seq_before + 1)


def test_dirty_row_bitmap_tracks_writes():
    ns = "dirty"
    world = synthetic_cascade_world(12, n_roots=1, seed=8, namespace=ns)
    client = MockClusterClient(world)
    client.get_columnar(ns)             # builds the master
    master = world._columnar[ns]
    master.build_view()                 # consume the build's dirty rows
    assert not master.cols.dirty[: master.cols.n].any()
    name = world.pods[ns][4]["metadata"]["name"]
    world.touch("pod", ns, name)
    master.refresh()
    dirty = np.flatnonzero(master.cols.dirty[: master.cols.n])
    assert dirty.tolist() == [4]        # exactly the touched row
    master.build_view()
    assert not master.cols.dirty[: master.cols.n].any()


def test_scan_text_cached_matches_scan_text():
    from rca_tpu.features.logscan import scan_text, scan_text_cached

    texts = [
        "", "INFO: fine", "ERROR: connection refused\nOOMKilled",
        "deadline exceeded " * 50,
    ]
    for t in texts:
        a, b = scan_text(t), scan_text_cached(t)
        assert np.array_equal(a, b)
    # cached result is a fresh array each call (no aliased mutation)
    x = scan_text_cached(texts[2])
    x[0] = 999
    assert scan_text_cached(texts[2])[0] != 999


def test_crc_digest_is_stable_and_content_sensitive():
    from rca_tpu.replay.format import digest_array_crc

    a = np.arange(24, dtype=np.float32).reshape(4, 6)
    d1 = digest_array_crc(a)
    assert d1 == digest_array_crc(a.copy())
    b = a.copy()
    b[2, 3] += 1e-3
    assert digest_array_crc(b) != d1
    # shape is part of the identity
    assert digest_array_crc(a.reshape(6, 4)) != d1


# -- bulk staging (update_rows) ----------------------------------------------

def test_update_rows_matches_update_many_bitwise():
    from rca_tpu.engine.streaming import StreamingSession

    rng = np.random.default_rng(3)
    n, feats = 50, 13
    names = [f"s{i}" for i in range(n)]
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    eng = GraphEngine()
    base = rng.uniform(0, 1, (n, feats)).astype(np.float32)

    a = StreamingSession(names, src, dst, num_features=feats, engine=eng)
    b = StreamingSession(names, src, dst, num_features=feats, engine=eng)
    a.set_all(base)
    b.set_all(base)
    for step in range(4):
        idx = rng.choice(n, size=int(rng.integers(1, 12)), replace=False)
        rows = rng.uniform(0, 1, (len(idx), feats)).astype(np.float32)
        a.update_many({int(i): rows[j] for j, i in enumerate(idx)})
        b.update_rows(idx.astype(np.int64), rows)
        if step == 2:
            # mixed staging: a later per-index update must win over the
            # block on both sessions
            override = rng.uniform(0, 1, feats).astype(np.float32)
            a.update(int(idx[0]), override)
            b.update(int(idx[0]), override)
        out_a, out_b = a.tick(), b.tick()
        assert out_a["upload_rows"] == out_b["upload_rows"]
        assert [
            (r["component"], r["score"]) for r in out_a["ranked"]
        ] == [
            (r["component"], r["score"]) for r in out_b["ranked"]
        ], f"step {step}"
        assert np.asarray(a._features).tobytes() == np.asarray(
            b._features
        ).tobytes()


def test_columnar_env_knob_round_trip(monkeypatch):
    from rca_tpu.config import columnar_enabled

    assert columnar_enabled() is True
    monkeypatch.setenv("RCA_COLUMNAR", "0")
    assert columnar_enabled() is False
    monkeypatch.setenv("RCA_COLUMNAR", "maybe")
    with pytest.raises(ValueError):
        columnar_enabled()
