"""Coordinator: pipelines, fusion backends (+ parity gate), chat turns,
suggestion dispatch, hypothesis workflow."""

import json

import pytest

from rca_tpu.agents import ALL_AGENT_TYPES, AnalysisContext
from rca_tpu.cluster.fixtures import NS, five_service_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.coordinator import (
    RCACoordinator,
    correlate_deterministic,
    correlate_findings,
    correlate_jax,
)
from rca_tpu.obslog import EvidenceLogger


@pytest.fixture(scope="module")
def client():
    return MockClusterClient(five_service_world())


@pytest.fixture(scope="module")
def coord(client, tmp_path_factory):
    return RCACoordinator(
        client,
        evidence_logger=EvidenceLogger(
            root=str(tmp_path_factory.mktemp("ev"))
        ),
    )


@pytest.fixture(scope="module")
def ctx(client):
    return AnalysisContext(ClusterSnapshot.capture(client, NS))


@pytest.fixture(scope="module")
def comprehensive(coord, ctx):
    return coord.run_analysis("comprehensive", NS, ctx=ctx)


def test_session_registry(coord):
    aid = coord.init_analysis("metrics", NS)
    st = coord.get_analysis_status(aid)
    assert st["status"] == "initialized"
    assert st["config"]["namespace"] == NS
    assert any(a["id"] == aid for a in coord.list_analyses())
    assert "error" in coord.get_analysis_status("nope")


def test_single_agent_analysis(coord, ctx):
    rec = coord.run_analysis("logs", NS, ctx=ctx)
    assert rec["status"] == "completed"
    assert rec["results"]["logs"]["findings"]
    assert rec["summary"]


def test_unknown_analysis_type_fails_cleanly(coord, ctx):
    rec = coord.run_analysis("bogus", NS, ctx=ctx)
    assert rec["status"] == "failed"
    assert "unknown analysis type" in rec["error"]


def test_comprehensive_pipeline(comprehensive):
    rec = comprehensive
    assert rec["status"] == "completed"
    results = rec["results"]
    for agent_type in ALL_AGENT_TYPES:
        assert agent_type in results
        assert "findings" in results[agent_type]
    correlated = results["correlated"]
    assert correlated["root_causes"]
    # the two injected fault roots dominate the ranking
    top2 = {r["component"] for r in correlated["root_causes"][:2]}
    assert top2 == {"database", "api-gateway"}
    assert results["summary"]
    json.dumps(rec, default=str)  # fully serializable


def test_parity_gate_jax_vs_deterministic(comprehensive, ctx):
    """North-star acceptance gate (BASELINE.md): the jax backend must carry
    the SAME grouped findings as the deterministic CPU coordinator on the
    50-service-class fixture — identical groups, identical members — and
    agree on the top root cause."""
    agent_results = {
        k: v for k, v in comprehensive["results"].items()
        if isinstance(v, dict) and "findings" in v
    }
    det = correlate_deterministic(agent_results)
    jx = correlate_jax(agent_results, ctx)

    def normalize(groups):
        return {
            comp: sorted(
                json.dumps(
                    {k: f[k] for k in ("issue", "severity", "source")},
                    sort_keys=True,
                )
                for f in findings
            )
            for comp, findings in groups.items()
        }

    assert normalize(det["groups"]) == normalize(jx["groups"])
    # top root cause agrees at the service level (det ranks the raw pod
    # component; jax ranks the owning service)
    from rca_tpu.coordinator.correlate import _component_service

    det_top_svc = _component_service(
        det["root_causes"][0]["component"],
        ctx.features.service_names,
    )
    assert det_top_svc in ("database", "api-gateway")
    assert jx["root_causes"][0]["component"] in ("database", "api-gateway")
    # every component with findings appears in both rankings
    det_comps = {r["component"] for r in det["root_causes"]}
    jx_comps = {r["component"] for r in jx["root_causes"]}
    assert det_comps <= jx_comps | set(det["groups"])


def test_parity_gate_50svc_findings_json_identical(fifty_svc_client):
    """BASELINE.md row 1, as written: on the 50-service fixture the jax
    backend's findings JSON must be IDENTICAL to the deterministic CPU
    coordinator's — byte-identical per-agent findings and groups from two
    fully independent pipeline runs (separate snapshot captures), plus an
    explicit ranking contract: the engine's top root cause is the injected
    fault root, and it owns the deterministic backend's top component."""
    ns = "synthetic"
    det_coord = RCACoordinator(fifty_svc_client, backend="deterministic")
    jax_coord = RCACoordinator(fifty_svc_client, backend="jax")
    rec_det = det_coord.run_analysis("comprehensive", ns)
    rec_jax = jax_coord.run_analysis("comprehensive", ns)
    assert rec_det["status"] == "completed"
    assert rec_jax["status"] == "completed"

    def findings_json(rec):
        """Per-agent findings exactly as rendered, canonical ordering."""
        return json.dumps(
            {
                agent: rec["results"][agent]["findings"]
                for agent in ALL_AGENT_TYPES
            },
            sort_keys=True, default=str,
        )

    assert findings_json(rec_det) == findings_json(rec_jax)
    det_corr = rec_det["results"]["correlated"]
    jax_corr = rec_jax["results"]["correlated"]
    assert det_corr["backend"] == "deterministic"
    # a degraded run records why (correlate_findings fallback channel) —
    # surface it so a rare engine failure here is diagnosable, not a bare
    # string mismatch
    assert jax_corr["backend"] == "jax", (
        f"jax backend degraded: from={jax_corr.get('fallback_from')} "
        f"reason={jax_corr.get('fallback_reason')}"
    )
    # grouped findings byte-identical across backends
    assert (
        json.dumps(det_corr["groups"], sort_keys=True, default=str)
        == json.dumps(jax_corr["groups"], sort_keys=True, default=str)
    )
    # ranking contract: jax ranks services, det ranks raw components;
    # the engine's top-1 must be the injected fault root and must own
    # the deterministic top component
    from rca_tpu.coordinator.correlate import _component_service

    roots = set(fifty_svc_client.world.ground_truth["fault_roots"])
    jax_top = jax_corr["root_causes"][0]["component"]
    assert jax_top in roots
    svc_names = AnalysisContext(
        ClusterSnapshot.capture(fifty_svc_client, ns)
    ).features.service_names
    det_top_svc = _component_service(
        det_corr["root_causes"][0]["component"], svc_names
    )
    assert det_top_svc == jax_top
    # every component the deterministic backend ranked appears in the jax
    # ranking, either directly or via its owning service
    jax_ranked = {r["component"] for r in jax_corr["root_causes"]}
    for r in det_corr["root_causes"]:
        comp = r["component"]
        svc = _component_service(comp, svc_names)
        assert comp in jax_ranked or svc in jax_ranked or comp in jax_corr["groups"]


def test_parity_gate_sharded_engine_behind_analyze(
    fifty_svc_client, monkeypatch
):
    """SURVEY §2.9: the sharded multi-device engine lives BEHIND the
    analyze boundary.  With RCA_SHARD=sp=4,dp=2 the UNCHANGED coordinator
    pipeline must route correlation through ShardedGraphEngine on the
    virtual 8-device mesh (the result records which engine ran) and
    produce byte-identical groups and the same ranked components as the
    single-device engine."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    ns = "synthetic"
    monkeypatch.delenv("RCA_SHARD", raising=False)
    monkeypatch.setenv("RCA_SHARD", "off")
    rec_single = RCACoordinator(
        fifty_svc_client, backend="jax"
    ).run_analysis("comprehensive", ns)
    monkeypatch.setenv("RCA_SHARD", "sp=4,dp=2")
    rec_shard = RCACoordinator(
        fifty_svc_client, backend="jax"
    ).run_analysis("comprehensive", ns)
    s_corr = rec_shard["results"]["correlated"]
    d_corr = rec_single["results"]["correlated"]
    assert d_corr["backend"] == "jax" and s_corr["backend"] == "jax", (
        f"degraded: single={d_corr.get('fallback_reason')} "
        f"sharded={s_corr.get('fallback_reason')}"
    )
    assert d_corr["engine"] == "single"
    assert s_corr["engine"] == "sharded(dp=2,sp=4)"
    assert (
        json.dumps(d_corr["groups"], sort_keys=True, default=str)
        == json.dumps(s_corr["groups"], sort_keys=True, default=str)
    )
    assert (
        [r["component"] for r in s_corr["root_causes"]]
        == [r["component"] for r in d_corr["root_causes"]]
    )
    # per-service scores and diagnostics agree within fp tolerance
    for rs, rd in zip(s_corr["root_causes"], d_corr["root_causes"]):
        assert abs(rs["score"] - rd["score"]) < 1e-4
    roots = set(fifty_svc_client.world.ground_truth["fault_roots"])
    assert s_corr["root_causes"][0]["component"] in roots


def test_correlate_backend_fallback(ctx):
    # no ctx -> jax backend degrades to deterministic AND says so
    out = correlate_findings(
        {"logs": {"findings": [{"component": "Pod/x", "issue": "boom",
                                "severity": "high"}]}},
        ctx=None, backend="jax",
    )
    assert out["backend"] == "deterministic"
    assert out["fallback_from"] == "jax"
    assert "AnalysisContext" in out["fallback_reason"]
    assert out["root_causes"][0]["component"] == "Pod/x"

    # an explicitly requested deterministic run carries no fallback keys
    chosen = correlate_findings(
        {"logs": {"findings": [{"component": "Pod/x", "issue": "boom",
                                "severity": "high"}]}},
        ctx=None, backend="deterministic",
    )
    assert "fallback_from" not in chosen

    # a jax engine that raises mid-run degrades with the exception recorded
    class _Boom:
        def analyze_features(self, *a, **k):
            raise RuntimeError("engine exploded")

    out2 = correlate_findings(
        {"logs": {"findings": [{"component": "Pod/x", "issue": "boom",
                                "severity": "high"}]}},
        ctx=ctx, backend="jax", engine=_Boom(),
    )
    assert out2["backend"] == "deterministic"
    assert out2["fallback_from"] == "jax"
    assert "engine exploded" in out2["fallback_reason"]


def test_process_user_query_structured(coord, ctx):
    out = coord.process_user_query(
        "what is wrong with my pods?", NS, ctx=ctx
    )
    assert out["response_data"]["points"]
    assert out["summary"]
    assert out["suggestions"]
    for s in out["suggestions"]:
        assert set(s) >= {"text", "priority", "reasoning", "action"}
        assert s["action"]["type"] in (
            "run_agent", "check_resource", "check_logs", "check_events",
            "query",
        )
    assert out["key_findings"]
    state = out["cluster_state"]
    assert state["total_pods"] == 6
    assert state["pods_by_phase"]["Failed"] == 1
    # the crashlooping database pod ranks worst
    assert state["problem_pods"][0]["pod"].startswith(
        ("database", "api-gateway")
    )


def test_suggestion_dispatch_all_five_types(coord, ctx):
    cases = [
        {"type": "run_agent", "agent_type": "events"},
        {"type": "check_resource", "kind": "Deployment", "name": "database"},
        {"type": "check_logs", "pod_name": "database-7c9f8b6d5e-3x5qp",
         "previous": True},
        {"type": "check_events", "kind": "Pod",
         "name": "database-7c9f8b6d5e-3x5qp"},
        {"type": "query", "query": "how is the cluster?"},
    ]
    for action in cases:
        out = coord.process_suggestion(action, NS, ctx=ctx)
        assert "response" in out and "suggestions" in out, action["type"]
        assert out["suggestions"], action["type"]
        assert "key_findings" in out, action["type"]


def test_check_logs_classifies_error_patterns(coord, ctx):
    out = coord.process_suggestion(
        {"type": "check_logs", "pod_name": "database-7c9f8b6d5e-3x5qp"},
        NS, ctx=ctx,
    )
    assert any("exception" in k for k in out["key_findings"])


def test_merge_llm_structured_backfill_semantics():
    """Deterministic backfill survives weak/absent LLM fields (reference:
    mcp_coordinator.py:1370-1567), including the hermetic provider's
    canned placeholder summary — placeholder text must not displace the
    counts-derived summary a user can act on."""
    from rca_tpu.coordinator.structured import merge_llm_structured

    base = {
        "response_data": {"points": ["det point"], "sections": []},
        "summary": "2 pod(s) show problems; most severe: db-0",
        "suggestions": [{"text": "det", "priority": "high",
                         "reasoning": "", "action": {"type": "query"}}],
        "key_findings": ["det finding"],
    }
    # None / non-dict → base unchanged
    assert merge_llm_structured(base, None) == base
    # the offline provider's canned summary is NOT an improvement
    out = merge_llm_structured(
        base, {"summary": "offline deterministic analysis"}
    )
    assert out["summary"] == base["summary"]
    # a real summary IS taken; malformed suggestions are dropped in favor
    # of the deterministic list
    out = merge_llm_structured(
        base,
        {"summary": "  db-0 is crash-looping  ",
         "suggestions": [{"no_text": True}]},
    )
    assert out["summary"] == "db-0 is crash-looping"
    assert out["suggestions"] == base["suggestions"]


def test_update_suggestions_drops_taken_action(coord, ctx):
    taken = {"type": "run_agent", "agent_type": "comprehensive"}
    fresh = coord.update_suggestions_after_action(taken, {}, NS, ctx=ctx)
    assert fresh
    assert all(
        s.get("action") != taken for s in fresh
    )


def test_followups_are_evidence_conditioned(ctx):
    """VERDICT r2 item 5: different evidence must yield DIFFERENT, targeted
    suggestions that name the objects the evidence implicates (the round-2
    version returned the same counts-derived list for every branch)."""
    import numpy as np

    from rca_tpu.coordinator.followups import evidence_followups
    from rca_tpu.features.logscan import LOG_PATTERN_NAMES

    def counts(**hits):
        c = np.zeros(len(LOG_PATTERN_NAMES))
        for name, n in hits.items():
            c[LOG_PATTERN_NAMES.index(name)] = n
        return c

    oom_logs = evidence_followups(ctx, {
        "kind": "logs", "pod": "cache-0",
        "pattern_counts": counts(oom_kill=40), "previous": False,
    })
    net_logs = evidence_followups(ctx, {
        "kind": "logs", "pod": "web-1",
        "pattern_counts": counts(connection_refused=7, dns_resolution=2),
        "previous": False,
    })
    sched_events = evidence_followups(ctx, {
        "kind": "events",
        "events": [{"reason": "FailedScheduling",
                    "involved_object": {"kind": "Pod", "name": "big-0"}}],
    })

    def actions(suggs):
        return [json.dumps(s["action"], sort_keys=True) for s in suggs]

    # three evidences, three different suggestion lists
    assert len({tuple(actions(s))
                for s in (oom_logs, net_logs, sched_events)}) == 3
    # 40 OOM-kill hits → describe THAT pod (memory limits), named
    top = oom_logs[0]
    assert top["action"] == {"type": "check_resource", "kind": "Pod",
                             "name": "cache-0"}
    assert "oom" in top["reasoning"].lower()
    # connection refusals → trace the dependency via the topology agent
    assert any(
        s["action"] == {"type": "run_agent", "agent_type": "topology"}
        and "web-1" in s["reasoning"]
        for s in net_logs
    ), net_logs
    # FailedScheduling → resource-pressure analysis naming the pod
    assert any(
        s["action"].get("agent_type") == "resources"
        and "big-0" in s["text"]
        for s in sched_events
    ), sched_events


def test_followups_fall_back_to_generics_on_quiet_evidence(ctx):
    """Unremarkable evidence degrades to the counts-derived generics —
    the list is never empty."""
    import numpy as np

    from rca_tpu.coordinator.followups import evidence_followups
    from rca_tpu.features.logscan import LOG_PATTERN_NAMES

    out = evidence_followups(ctx, {
        "kind": "logs", "pod": "quiet-0",
        "pattern_counts": np.zeros(len(LOG_PATTERN_NAMES)),
        "previous": False,
    })
    assert out
    # generic tier: driven by cluster counts, not the quiet pod
    assert all("quiet-0" not in json.dumps(s) for s in out)


def test_update_suggestions_consume_result_evidence(coord, ctx):
    """After an action, the regenerated list is conditioned on what that
    action just found (result.evidence_tag), not only on cluster counts."""
    crash_pod = "database-7c9f8b6d5e-3x5qp"
    taken = {"type": "check_logs", "pod_name": crash_pod}
    result = coord.process_suggestion(taken, NS, ctx=ctx)
    assert result.get("evidence_tag", {}).get("kind") == "logs"
    fresh = coord.update_suggestions_after_action(taken, result, NS, ctx=ctx)
    # the taken action itself is dropped...
    assert all(
        json.dumps(s["action"], sort_keys=True, default=str)
        != json.dumps(taken, sort_keys=True, default=str)
        for s in fresh
    )
    # ...but its evidence still steers the follow-ups at the pod
    assert any(
        crash_pod in json.dumps(s["action"], default=str) for s in fresh
    ), fresh


def test_hypothesis_workflow_end_to_end(coord, ctx):
    finding = {
        "issue": "pod stuck in CrashLoopBackOff",
        "severity": "critical",
        "evidence": {"restarts": 5},
        "recommendation": "read previous logs",
    }
    comp = "Pod/database-7c9f8b6d5e-3x5qp"
    hyps = coord.generate_hypotheses(finding=finding, component=comp,
                                     namespace=NS, investigation_id="inv-t")
    assert 3 <= len(hyps) <= 5
    assert all(0 < h["confidence"] <= 1 for h in hyps)
    assert hyps == sorted(hyps, key=lambda h: -h["confidence"])
    # evidence logger captured the hypotheses
    assert coord.evidence.get_evidence_for_hypothesis(
        hyps[0]["description"][:20]
    )

    plan = coord.get_investigation_plan(hyps[0], NS)
    assert plan["steps"]
    assert plan["steps"][0]["status"] == "pending"

    executed = []
    for step in plan["steps"]:
        out = coord.execute_investigation_step(
            step, hyps[0], NS, investigation_id="inv-t"
        )
        assert out["verdict"]["verdict"] in (
            "supported", "refuted", "inconclusive"
        )
        executed.append(out)
    # the database's error logs should support the crash hypothesis
    assert any(o["verdict"]["verdict"] == "supported" for o in executed)

    report = coord.generate_root_cause_report(
        {
            "component": comp,
            "accepted_hypothesis": hyps[0],
            "steps": executed,
            "finding": finding,
        }
    )
    assert "Root Cause Report" in report
    assert comp in report


def test_jax_backend_reports_latency(comprehensive):
    correlated = comprehensive["results"]["correlated"]
    assert correlated["backend"] == "jax"
    assert correlated["engine_latency_ms"] > 0
