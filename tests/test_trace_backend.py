"""Jaeger trace-backend conformance: the adapter, driven from recorded
Jaeger query-API JSON (injected opener, no network), must serve the SAME
shapes as MockClusterClient's trace surface — so the traces agent, the
feature extractor's error-rate/latency channels, and the trace-derived
dependency edges work identically against live and mock backends
(VERDICT r3 item 5)."""

from __future__ import annotations

import json
import urllib.parse

import pytest

from rca_tpu.cluster.trace_backend import JaegerTraceBackend

US = 1000  # microseconds per ms


def _span(span_id, op, start_ms, dur_ms, process="p1", error=False,
          status=None, trace_id="t1"):
    tags = []
    if error:
        tags.append({"key": "error", "value": True})
    if status is not None:
        tags.append({"key": "http.status_code", "value": status})
    return {
        "traceID": trace_id, "spanID": span_id, "operationName": op,
        "startTime": start_ms * US, "duration": dur_ms * US,
        "processID": process, "tags": tags,
    }


def _trace(trace_id, spans, processes):
    return {"traceID": trace_id, "spans": spans, "processes": processes}


PROCS = {"p1": {"serviceName": "frontend"}, "p2": {"serviceName": "backend"}}

TRACE_A = _trace(
    "abc123",
    [
        _span("s1", "GET /", 1000, 200, "p1", trace_id="abc123"),
        _span("s2", "SELECT", 1050, 600, "p2", error=True,
              trace_id="abc123"),
    ],
    PROCS,
)
TRACE_B = _trace(
    "def456",
    [
        _span("s3", "GET /", 2000, 40, "p1", trace_id="def456"),
        _span("s4", "SELECT", 2010, 20, "p2", status="503",
              trace_id="def456"),
    ],
    PROCS,
)

RECORDED = {
    "/api/services": {"data": ["frontend", "backend"]},
    "/api/traces?service=frontend": {"data": [TRACE_A, TRACE_B]},
    "/api/traces?service=backend": {"data": [TRACE_A, TRACE_B]},
    "/api/traces/abc123": {"data": [TRACE_A]},
    "/api/dependencies": {"data": [
        {"parent": "frontend", "child": "backend", "callCount": 42},
    ]},
}


def _opener(url: str) -> bytes:
    parsed = urllib.parse.urlparse(url)
    key = parsed.path
    qs = urllib.parse.parse_qs(parsed.query)
    if key == "/api/traces" and "service" in qs:
        key = f"/api/traces?service={qs['service'][0]}"
    payload = RECORDED.get(key)
    if payload is None:
        raise AssertionError(f"unexpected request: {url}")
    return json.dumps(payload).encode()


@pytest.fixture()
def backend():
    return JaegerTraceBackend("http://jaeger:16686", opener=_opener)


def test_trace_ids_and_details(backend):
    ids = backend.trace_ids("ns", limit=10)
    assert ids == ["abc123", "def456"]
    det = backend.trace_details("abc123")
    assert det["trace_id"] == "abc123"
    assert det["services"] == ["backend", "frontend"]
    assert det["span_count"] == 2
    # trace spans 1000ms..1650ms -> 650ms end to end
    assert det["duration_ms"] == pytest.approx(650.0)
    assert any(s["error"] for s in det["spans"])


def test_latency_stats_mock_twin_shape(backend):
    stats = backend.service_latency_stats("ns")
    assert set(stats) == {"frontend", "backend"}
    for svc in stats:
        assert set(stats[svc]) == {"p50", "p95", "p99"}
        assert stats[svc]["p50"] <= stats[svc]["p99"]
    # backend spans: 600ms and 20ms per sampled trace
    assert stats["backend"]["p99"] == pytest.approx(600.0)


def test_error_rates_from_tags_and_status(backend):
    rates = backend.error_rate_by_service("ns")
    # every backend span errored (error tag / 503); frontend spans clean
    assert rates["backend"] == pytest.approx(1.0)
    assert rates["frontend"] == pytest.approx(0.0)


def test_dependencies_shape(backend):
    deps = backend.service_dependencies("ns")
    assert deps == {"frontend": ["backend"]}


def test_slow_operations_sorted(backend):
    ops = backend.find_slow_operations("ns", threshold_ms=100.0)
    assert ops and ops[0]["duration_ms"] >= ops[-1]["duration_ms"]
    assert {"service", "operation", "duration_ms", "trace_id"} <= set(ops[0])
    assert all(op["duration_ms"] >= 100.0 for op in ops)


def test_transport_failure_degrades_and_records(monkeypatch):
    def dead(url):
        raise OSError("connection refused")

    b = JaegerTraceBackend("http://jaeger:16686", opener=dead)
    assert b.service_latency_stats("ns") == {}
    assert b.trace_ids("ns") == []
    assert b.errors  # failures recorded, never raised


def test_live_client_gates_on_env(monkeypatch):
    """Unset RCA_TRACE_ENDPOINT -> the live client's historical empty
    structures; set -> real structures through the adapter, with transport
    failures landing in the degraded-mode error channel."""
    from rca_tpu.cluster.k8s_client import K8sApiClient

    client = K8sApiClient.__new__(K8sApiClient)
    client._errors = []
    monkeypatch.delenv("RCA_TRACE_ENDPOINT", raising=False)
    assert client.get_service_latency_stats("ns") == {}
    assert client.get_trace_ids("ns") == []

    client2 = K8sApiClient.__new__(K8sApiClient)
    client2._errors = []
    monkeypatch.setenv("RCA_TRACE_ENDPOINT", "jaeger:http://jaeger:16686")
    backend = client2._traces()
    assert backend is not None and backend.endpoint == "http://jaeger:16686"
    backend._opener = _opener
    stats = client2.get_service_latency_stats("ns")
    assert set(stats) == {"frontend", "backend"}
    deps = client2.get_service_dependencies("ns")
    assert deps == {"frontend": ["backend"]}


def test_mock_twin_conformance_via_extractor(monkeypatch):
    """The decisive parity check: the feature extractor consumes the
    adapter's structures exactly as it consumes the mock's — error-rate
    and latency channels light up from recorded Jaeger data."""
    import numpy as np

    from rca_tpu.cluster.k8s_client import K8sApiClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.features.extract import extract_features
    from rca_tpu.features.schema import SvcF

    client = K8sApiClient.__new__(K8sApiClient)
    client._errors = []
    client._connected = False
    client._kubectl = None
    for attr in ("_core", "_apps", "_net", "_batch", "_autoscaling"):
        setattr(client, attr, None)
    monkeypatch.setenv("RCA_TRACE_ENDPOINT", "http://jaeger:16686")
    backend = client._traces()
    backend._opener = _opener

    snap = ClusterSnapshot.capture(client, "ns")
    # no cluster: pods/services come back empty, traces are REAL
    assert snap.traces["error_rates"]["backend"] == pytest.approx(1.0)
    assert snap.traces["dependencies"] == {"frontend": ["backend"]}

    # graft the trace payload onto a mock world snapshot: services whose
    # names match get their channels from the recorded data
    import dataclasses

    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.world import (
        World, make_deployment, make_endpoints, make_pod, make_service,
    )

    w = World(cluster_name="t")
    for svc in ("frontend", "backend"):
        w.add("pods", "ns", make_pod(f"{svc}-0", "ns", svc))
        w.add("services", "ns", make_service(svc, "ns"))
        w.add("deployments", "ns", make_deployment(svc, "ns", svc))
        w.add("endpoints", "ns", make_endpoints(svc, "ns", [f"{svc}-0"]))
    base = ClusterSnapshot.capture(MockClusterClient(w), "ns")
    grafted = dataclasses.replace(base, traces=snap.traces)
    fs = extract_features(grafted)
    i = fs.service_names.index("backend")
    assert fs.service_features[i, SvcF.ERROR_RATE] == pytest.approx(1.0)
    assert float(np.max(fs.service_features[:, SvcF.LATENCY])) >= 0.0
