"""Structure-fuzz: the pipeline must survive malformed cluster objects.

Live clusters produce partially-serialized objects (`metadata: null`,
containers without names, statuses stripped by RBAC) — the reference's
archived evidence files record AttributeErrors from exactly this input
class (reference: logs/archive/*_hypothesis.json per SURVEY.md §2.6).
Normalization happens ONCE at the snapshot boundary
(rca_tpu/cluster/sanitize.py); these tests mangle the 5-service world with
seeded random deletions/nullings and require every backend's comprehensive
analysis to COMPLETE (degraded findings are fine, crashes are not).

Before the sanitizer existed, 72 of 80 of these runs failed.
"""

import random

import pytest

from rca_tpu.cluster.fixtures import NS, five_service_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.sanitize import sanitize_object, sanitize_objects
from rca_tpu.coordinator import RCACoordinator


def _mangle(obj, rng):
    if isinstance(obj, dict):
        for k in list(obj):
            r = rng.random()
            if r < 0.08:
                del obj[k]
            elif r < 0.12:
                obj[k] = None
            else:
                _mangle(obj[k], rng)
    elif isinstance(obj, list):
        for i, item in enumerate(obj):
            if rng.random() < 0.04:
                obj[i] = None  # null ELEMENTS, not just null values
            else:
                _mangle(item, rng)


@pytest.mark.parametrize("seed", [0, 3, 5, 10, 13, 14, 16, 19, 22, 31])
def test_comprehensive_survives_mangled_world(seed):
    rng = random.Random(seed)
    world = five_service_world()
    for coll in (world.pods, world.services, world.deployments,
                 world.events, world.endpoints, world.hpas,
                 world.network_policies, world.ingresses):
        _mangle(coll.get(NS, []), rng)
    client = MockClusterClient(world)
    for backend in ("deterministic", "jax"):
        rec = RCACoordinator(client, backend=backend).run_analysis(
            "comprehensive", NS
        )
        assert rec["status"] == "completed", (
            f"seed {seed} backend {backend}: {rec.get('error', '')[:300]}"
        )


def test_sanitize_invariants():
    pod = {
        "metadata": None,
        "spec": {"containers": [{"name": None, "env": [
            {"name": None, "value": None},
        ]}]},
        "status": {
            "phase": None,
            "containerStatuses": None,
            "conditions": [{"type": None, "status": "False"}],
        },
    }
    clean = sanitize_objects([pod, "not-a-dict", None])
    # a null element of an object list becomes a named empty object, never
    # a nested [] (the parent_key-recursion trap)
    holey = sanitize_object(
        {"spec": {"containers": [None, {"name": "c"}]},
         "status": {"containerStatuses": [None]}}
    )
    assert holey["spec"]["containers"][0] == {"name": ""}
    assert holey["status"]["containerStatuses"][0] == {"name": ""}
    # nested metadata: null carries the full invariant
    tmpl = sanitize_object({"template": {"metadata": None, "spec": {}}})
    assert tmpl["template"]["metadata"] == {"name": "", "labels": {}}
    # ... and so does a WRONG-TYPED metadata (string/int) — the dict
    # coercion must emit the repaired form, not a bare {}
    for bad in ("x", 123, ["y"]):
        wrong = sanitize_object({"template": {"metadata": bad}})
        assert wrong["template"]["metadata"] == {"name": "", "labels": {}}
    assert len(clean) == 1  # non-dict entries dropped
    p = clean[0]
    assert p["metadata"] == {"name": "", "labels": {}}
    assert p["status"]["containerStatuses"] == []
    assert p["status"]["phase"] == ""
    c = p["spec"]["containers"][0]
    assert c["name"] == ""
    assert c["env"][0]["name"] == "" and c["env"][0]["value"] == ""
    assert p["status"]["conditions"][0]["type"] == ""

    # label maps coerce values to strings for selector matching / scans
    svc = sanitize_object(
        {"metadata": {"name": "s", "labels": {"app": None, "tier": 3}}}
    )
    assert svc["metadata"]["labels"] == {"app": "", "tier": "3"}

    # well-formed objects pass through unchanged — INCLUDING condition
    # entries, whose "status" is a STRING ('True'/'False'), not the
    # object-level status dict (a context-free coercion wiped these to {}
    # and made every healthy node read as NotReady)
    good = {
        "metadata": {"name": "x", "labels": {"app": "x"}},
        "spec": {"containers": [{"name": "c", "image": "busybox"}]},
        "status": {
            "phase": "Running", "containerStatuses": [],
            "conditions": [
                {"type": "Ready", "status": "True"},
                {"type": "MemoryPressure", "status": "False"},
            ],
        },
    }
    assert sanitize_objects([good]) == [good]
    # and a null condition status stays None (unknown), never becomes {}
    cond = sanitize_object(
        {"status": {"conditions": [{"type": "Ready", "status": None}]}}
    )
    assert cond["status"]["conditions"][0]["status"] is None


def test_healthy_world_capture_uncorrupted():
    """End-to-end guard for the conditions-status regression: capturing
    the healthy fixture must keep node conditions verbatim and produce NO
    node-condition findings from the events agent."""
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot

    world = five_service_world()
    snap = ClusterSnapshot.capture(MockClusterClient(world), NS)
    for node in snap.nodes:
        for cond in node.get("status", {}).get("conditions", []):
            assert isinstance(cond.get("status"), (str, type(None))), cond


def test_sanitize_idempotent():
    """sanitize(sanitize(x)) == sanitize(x): the output must already satisfy
    every invariant, for Python and native alike."""
    import copy

    from rca_tpu.native import load_sanitize

    mangled = {
        "metadata": None,
        "spec": {"containers": [None, {"name": None, "env": [
            {"name": None}, None,
        ]}], "template": {"metadata": None}},
        "status": {"phase": None, "conditions": [{"type": None,
                                                  "status": None}]},
        "labels-like": {"a": None},
    }
    once = sanitize_object(copy.deepcopy(mangled))
    twice = sanitize_object(copy.deepcopy(once))
    assert twice == once
    native = load_sanitize()
    if native is not None:
        n_once = native.sanitize_object(copy.deepcopy(mangled))
        assert native.sanitize_object(copy.deepcopy(n_once)) == n_once
        assert n_once == once
