"""Engine tests: explain-away semantics, hit@1 on synthetic cascades,
snapshot path on the 5-service fixture, bucket padding invariance."""

import numpy as np

from rca_tpu.cluster.fixtures import NS
from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.engine import GraphEngine
from rca_tpu.features.schema import NUM_SERVICE_FEATURES, SvcF


def _chain_case():
    """0 depends on 1 depends on 2; 2 is crashed, 0/1 degraded.  Three
    PERFECTLY healthy bystanders (all-zero features — the normal shape of
    real extracted snapshots) anchor the background median at zero; impact
    is background-relative (propagate.background_excess) and must treat
    quiet-but-live services as background, not as padding."""
    f = np.zeros((6, NUM_SERVICE_FEATURES), np.float32)
    f[2, SvcF.CRASH] = 1.0
    f[2, SvcF.NOT_READY] = 1.0
    f[1, SvcF.ERROR_RATE] = 0.6
    f[1, SvcF.LATENCY] = 0.7
    f[0, SvcF.ERROR_RATE] = 0.4
    f[0, SvcF.LATENCY] = 0.5
    src = np.array([0, 1], np.int32)
    dst = np.array([1, 2], np.int32)
    return f, src, dst


def test_explain_away_chain():
    f, src, dst = _chain_case()
    res = GraphEngine().analyze_arrays(
        f, src, dst, ["a", "b", "c", "x", "y", "z"]
    )
    assert res.ranked[0]["component"] == "c"
    # the middle service is anomalous but explained by its broken dependency
    assert res.upstream[1] > 0.8
    assert res.score[1] < res.score[2]
    # impact flows downstream: the root accumulated its dependents'
    # above-background anomaly — nonzero even though every non-incident
    # service is exactly zero (clean-input regression)
    assert res.impact[2] > res.impact[1] > 0


def test_hit_at_1_single_root():
    hits = 0
    for seed in range(10):
        case = synthetic_cascade_arrays(200, n_roots=1, seed=seed)
        res = GraphEngine().analyze_case(case)
        hits += res.ranked[0]["component"] == case.names[case.roots[0]]
    assert hits == 10


def test_hit_at_k_multi_root():
    case = synthetic_cascade_arrays(500, n_roots=3, seed=42)
    res = GraphEngine().analyze_case(case, k=5)
    top5 = set(res.top_components(5))
    truth = {case.names[r] for r in case.roots.tolist()}
    assert truth <= top5


def test_snapshot_path_five_service(five_svc_client):
    snap = ClusterSnapshot.capture(five_svc_client, NS)
    res = GraphEngine().analyze_snapshot(snap)
    top2 = set(res.top_components(2))
    # both injected roots outrank the symptomatic mid-tier services
    assert top2 == {"database", "api-gateway"}


def test_bucket_padding_invariance():
    case = synthetic_cascade_arrays(60, n_roots=1, seed=9)
    engine = GraphEngine()
    res = engine.analyze_case(case)
    # same result when the graph is analyzed under a larger bucket
    from rca_tpu.config import RCAConfig

    big = GraphEngine(RCAConfig(shape_buckets=(1024,)))
    res2 = big.analyze_case(case)
    np.testing.assert_allclose(res.score, res2.score, atol=1e-6)
    assert res.top_components() == res2.top_components()


def test_empty_graph():
    f = np.zeros((4, NUM_SERVICE_FEATURES), np.float32)
    res = GraphEngine().analyze_arrays(
        f, np.zeros(0, np.int32), np.zeros(0, np.int32)
    )
    assert res.score.max() == 0.0
    assert len(res.ranked) <= 4


def test_propagation_permutation_equivariance():
    """Relabeling services must relabel scores identically: scores[perm] of
    the permuted problem == original scores.  Catches subtle indexing bugs
    in any edge layout (gather/scatter index mixups survive value-level
    tests because most entries look plausible)."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import GraphEngine

    case = synthetic_cascade_arrays(150, n_roots=2, seed=9)
    engine = GraphEngine()
    base = engine.analyze_arrays(case.features, case.dep_src, case.dep_dst)

    rng = np.random.default_rng(0)
    perm = rng.permutation(case.n)            # new_index = perm_pos of old
    inv = np.empty_like(perm)
    inv[perm] = np.arange(case.n)

    f2 = case.features[perm]                  # row i now holds old perm[i]
    src2 = inv[case.dep_src]
    dst2 = inv[case.dep_dst]
    out = engine.analyze_arrays(f2, src2, dst2)

    np.testing.assert_allclose(out.score, base.score[perm], atol=1e-6)
    np.testing.assert_allclose(out.impact, base.impact[perm], atol=1e-5)
    np.testing.assert_allclose(out.upstream, base.upstream[perm], atol=1e-6)


def test_hub_fanin_invariance():
    """Formula-v3 regression (the round-2 adversarial autopsy): a hub's
    impact term must measure its MEAN dependent symptom level, not a sum
    that grows with fan-in.  A mildly-noisy hub with many quiet-but-noisy
    dependents must not outrank a genuinely faulty root whose few
    dependents are heavily symptomatic — under the v2 raw-sum formula the
    hub's accumulated background saturated tanh and won every time the
    root's crash channel was dropped (tools/accuracy_report.py taxonomy:
    every band-1000/2000 miss's winner was an early-DAG hub)."""
    rng = np.random.default_rng(7)
    n = 300
    f = rng.uniform(0.0, 0.35, (n, NUM_SERVICE_FEATURES)).astype(np.float32)
    f[:, SvcF.CRASH] = 0.0
    # hub 0: everything else depends on it; its own signals are background
    hub_src = np.arange(1, n, dtype=np.int32)
    hub_dst = np.zeros(n - 1, np.int32)
    # root 250: no crash channel (dropped), soft signals only — but its two
    # dependents are saturated-symptomatic
    root, v1, v2 = 250, 251, 252
    f[root, SvcF.LOG_ERRORS] = 0.9
    f[root, SvcF.EVENTS] = 0.85
    f[root, SvcF.RESTARTS] = 0.6
    for v in (v1, v2):
        f[v, SvcF.ERROR_RATE] = 0.9
        f[v, SvcF.LATENCY] = 0.95
    src = np.concatenate([hub_src, np.array([v1, v2], np.int32)])
    dst = np.concatenate([hub_dst, np.array([root, root], np.int32)])
    res = GraphEngine().analyze_arrays(f, src, dst)
    assert res.score[root] > res.score[0], (
        f"hub (score {res.score[0]:.3f}, impact {res.impact[0]:.3f}) "
        f"outranks root (score {res.score[root]:.3f})"
    )
    # and the hub's impact mean stays at background level
    assert res.impact[0] < 0.5


def test_propagation_monotone_in_crash_signal():
    """Raising a service's crash evidence must not LOWER its own score
    (sanity of the scoring surface; guards weight-retune regressions)."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import GraphEngine
    from rca_tpu.features.schema import SvcF

    case = synthetic_cascade_arrays(120, n_roots=1, seed=4)
    engine = GraphEngine()
    victim = (int(case.roots[0]) + 17) % case.n
    base = engine.analyze_arrays(case.features, case.dep_src, case.dep_dst)
    bumped = case.features.copy()
    bumped[victim, SvcF.CRASH] = min(1.0, bumped[victim, SvcF.CRASH] + 0.5)
    out = engine.analyze_arrays(bumped, case.dep_src, case.dep_dst)
    assert out.score[victim] >= base.score[victim] - 1e-6


def test_analyze_batch_matches_single(monkeypatch):
    """One batched dispatch == a loop of single analyses (the hypothesis
    batch path, VERDICT r3 item 7), on both engines."""
    import jax
    import numpy as np

    from rca_tpu.engine import ShardedGraphEngine

    c = synthetic_cascade_arrays(300, n_roots=2, seed=3)
    rng = np.random.default_rng(0)
    B = 5
    batch = np.stack([
        np.clip(c.features + rng.uniform(0, 0.05, c.features.shape), 0, 1)
        .astype(np.float32)
        for _ in range(B)
    ])
    engines = [GraphEngine()]
    if len(jax.devices()) >= 8:
        engines.append(ShardedGraphEngine(spec="sp=4,dp=2"))
    for eng in engines:
        singles = [
            eng.analyze_arrays(batch[b], c.dep_src, c.dep_dst, c.names, k=5)
            for b in range(B)
        ]
        batched = eng.analyze_batch(batch, c.dep_src, c.dep_dst, c.names, k=5)
        assert len(batched) == B
        for s, b in zip(singles, batched):
            np.testing.assert_allclose(
                b.score, s.score, rtol=1e-5, atol=1e-6
            )
            assert b.top_components() == s.top_components()
        assert batched[0].engine.endswith("-batch")


def test_hypotheses_cli_counterfactual_support(capsys):
    """The counterfactual CLI ranks the true root's support highest:
    muting the root leaves its victims unexplained (their scores rise),
    muting a victim changes little."""
    import json as _json

    from rca_tpu.cli import main

    rc = main(["hypotheses", "--fixture", "50svc", "--candidates", "4",
               "--compact"])
    assert rc == 0
    out = _json.loads(capsys.readouterr().out)
    assert out["batch_width"] == 4
    ranked = out["hypotheses"]
    # seed-0 50svc fixture: svc-00024 is the ground-truth root
    assert ranked[0]["candidate"] == "svc-00024"
    assert ranked[0]["support"] > 0.5
    assert all(r["support"] < 0.5 for r in ranked[1:])
