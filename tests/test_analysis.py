"""graftlint (rca_tpu/analysis, ANALYSIS.md): every rule fires on its
fixture, suppressions and the baseline round-trip, the repo itself is
clean with an EMPTY baseline, and the dynamic tracecheck proves the
public engine entry points compile once."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from rca_tpu.analysis import (
    all_rules,
    default_baseline_path,
    load_baseline,
    repo_root,
    run_lint,
    write_baseline,
)

ROOT = repo_root()


# ---------------------------------------------------------------------------
# fixture snippets: one failing example per rule.  Each entry is
# (rule, path-inside-a-fake-repo, source, expected minimum finding count).
# ---------------------------------------------------------------------------

FIXTURES = {
    "tracer-leak": ("rca_tpu/engine/bad_tracer.py", """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    y = jnp.sum(x)
    if y > 0:                      # host branch on a traced value
        return y
    return float(y)                # host cast on a traced value
""", 2),
    "resident-fetch": ("rca_tpu/engine/runner.py", """\
import jax

def analyze_arrays(run):
    stacked, diag, vals, idx, n_bad = run()
    return jax.device_get(stacked)     # bulk fetch outside a surface

def render(handle):
    handle.stacked.block_until_ready() # stray sync in a render helper
    return handle
""", 2),
    "retrace-hazard": ("rca_tpu/engine/streaming.py", """\
import functools
import jax
import jax.numpy as jnp

def capture():
    return jnp.array([1.0, 2.0])   # per-call literal on the hot path

@jax.jit
def g(x):
    return jnp.where(x > 0)        # data-dependent output shape

@functools.partial(jax.jit, static_argnames=("opts",))
def h(x, opts=[1, 2]):             # unhashable static default
    return x
""", 3),
    "rng-key-reuse": ("rca_tpu/engine/bad_rng.py", """\
import jax

def sample():
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))   # same key, second draw
    return a, b

def loopy():
    key = jax.random.PRNGKey(0)
    out = []
    for _ in range(4):
        out.append(jax.random.normal(key, (2,)))  # reused per iteration
    return out
""", 2),
    "lock-discipline": ("rca_tpu/serve/bad_locks.py", """\
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def leaky(self):
        self._lock.acquire()       # no try/finally release
        self._items.pop()
        self._lock.release()
""", 1),
    "race-guard": ("rca_tpu/serve/bad_race.py", """\
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._done = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="w", daemon=True
        )
        self._thread.start()

    def _run(self):
        while True:
            self._done += 1        # unguarded RMW from the worker root

    def bump(self):
        with self._lock:
            self._done += 1        # the dominant guard, held by main
""", 1),
    "lock-order": ("rca_tpu/serve/bad_order.py", """\
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            self._inner_b()        # A -> B across a call boundary

    def _inner_b(self):
        with self._b:
            pass

    def backward(self):
        with self._b:
            with self._a:          # B -> A: closes the cycle
                pass
""", 1),
    "thread-discipline": ("rca_tpu/serve/bad_threads.py", """\
import multiprocessing
import os
import socket
import subprocess
import threading

def main(fn):
    lock = threading.Lock()        # raw lock outside util/threads.py
    t = threading.Thread(target=fn, args=(lock,))  # raw anonymous thread
    t.start()
    return t

def listener():
    return socket.socket()         # raw socket outside util/net.py

def children(argv):
    p = subprocess.Popen(argv)     # raw child outside util/procs.py
    pid = os.fork()                # ditto
    w = multiprocessing.Process(target=main)  # multiprocessing wholesale
    return p, pid, w
""", 6),
    "env-discipline": ("rca_tpu/engine/bad_env.py", """\
import os

def depth():
    return int(os.environ.get("RCA_PIPELINE_DEPTH", "1"))
""", 1),
    "tick-sync": ("rca_tpu/engine/live.py", """\
import jax

class S:
    def poll(self):
        return jax.device_get(self.x)   # sync outside fetch
""", 1),
    "swallowed-faults": ("rca_tpu/agents/bad_faults.py", """\
def f():
    try:
        g()
    except Exception:
        pass
""", 1),
    "nondet-discipline": ("rca_tpu/serve/bad_nondet.py", """\
import datetime
import random
import time

import numpy as np


def stamp():
    return time.time()              # wall read outside the clock seam


def jitter():
    return random.random()          # module-level (global-state) draw


def when():
    return datetime.datetime.now()  # wall read


def rng():
    return np.random.default_rng()  # unseeded constructor
""", 4),
    "no-dict-scan": ("rca_tpu/cluster/columnar.py", """\
import numpy as np


def build_view(table):
    \"\"\"[no-dict-scan] assemble the capture view.\"\"\"
    feat = table.base.copy()
    for i, pod in enumerate(table.objects):   # per-pod loop crept back
        feat[i, 0] = pod.get("x", 0.0)
    while feat.sum() < 0:                     # and a while for good measure
        break
    return feat


def encode_row(pod):
    # unmarked helper: row-write encoders MAY loop (paid per mutation)
    total = 0
    for cs in pod.get("statuses", []):
        total += cs.get("restarts", 0)
    return total
""", 2),
    "span-discipline": ("rca_tpu/serve/bad_spans.py", """\
from rca_tpu.observability.spans import Span


def handle(tracer, ctx):
    sp = tracer.span("serve.request", parent=ctx)  # never entered
    raw = Span("x", "t", "s", None, 0.0, 1.0)      # bypasses the seam
    return sp, raw
""", 2),
    "kernel-dispatch": ("rca_tpu/engine/bad_dispatch.py", """\
from rca_tpu.engine.doubling import doubling_layouts_for
from rca_tpu.engine.pallas_kernels import (
    noisy_or_pair_pallas,
    noisyor_autotune,
)
from rca_tpu.engine.quantized import quant_imp_step


def tick(ft, w, m, a_ex, src, dst, inv_deg):
    # re-deriving the kernel choice locally bypasses the registry seam
    if noisyor_autotune() == "pallas":
        return noisy_or_pair_pallas(ft, w, w)
    # the NEW kernels' bodies are seam-guarded too (ISSUE 13): calling
    # them outside engine/{quantized,doubling}.py is unlandable
    dbl = doubling_layouts_for(64, 64, src, dst, 8)
    return quant_imp_step(m, a_ex, 0.7, src, dst, inv_deg), dbl
""", 4),
}


def _fake_repo(tmp_path, *entries):
    """A minimal repo layout holding the given (relpath, source) files."""
    for rel, src in entries:
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(src)
    return str(tmp_path)


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_fires_on_fixture(tmp_path, rule):
    rel, src, expected = FIXTURES[rule]
    root = _fake_repo(tmp_path, (rel, src))
    result = run_lint(root=root, rules=[rule], use_baseline=False)
    got = [f for f in result.findings if f.rule == rule]
    assert len(got) >= expected, (
        f"{rule} found {len(got)} < {expected}: {result.findings}"
    )
    for f in got:
        assert f.path == rel
        assert f.snippet  # human output carries the flagged source line


def test_clean_twin_fixtures_pass(tmp_path):
    """The corrected twin of each fixture produces zero findings — the
    rules flag the bug, not the neighborhood."""
    root = _fake_repo(
        tmp_path,
        ("rca_tpu/engine/good_tracer.py", """\
import functools
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    y = jnp.sum(x)
    return jnp.where(y > 0, y, -y)

@functools.partial(jax.jit, static_argnames=("debug",))
def g(x, debug=False):
    if debug:                     # static arg: host branch is fine
        return x * 0
    if x.shape[0] > 4:            # shapes are static under trace
        return x
    return -x
"""),
        ("rca_tpu/engine/good_rng.py", """\
import jax

def sample():
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    return jax.random.normal(k1, (3,)), jax.random.uniform(k2, (3,))
"""),
        ("rca_tpu/serve/good_locks.py", """\
from rca_tpu.util.threads import make_lock

class Q:
    def __init__(self):
        self._lock = make_lock("Q._lock")
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def legacy_put(self, x):
        self._lock.acquire()
        try:
            self._items.append(x)
        finally:
            self._lock.release()
"""),
        ("rca_tpu/serve/good_race.py", """\
from rca_tpu.util.threads import make_lock, make_thread

class Worker:
    def __init__(self):
        self._lock = make_lock("Worker._lock")
        self._done = 0
        self._thread = None

    def start(self):
        self._thread = make_thread(self._run, name="w", daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                self._done += 1    # every write site holds the guard

    def bump(self):
        with self._lock:
            self._done += 1
"""),
        ("rca_tpu/gateway/good_socket.py", """\
from rca_tpu.util.net import make_server_socket

def listen(host, port):
    return make_server_socket("gateway", host, port)  # the seam itself
"""),
        ("rca_tpu/serve/good_procs.py", """\
import subprocess

from rca_tpu.util.procs import python_argv, spawn_worker

def launch(worker_id, addr):
    # long-lived children go through the seam...
    return spawn_worker(
        f"fed-worker{worker_id}",
        python_argv("rca_tpu.serve.worker", "--connect", addr),
    )

def one_shot(cmd):
    # ...one-shot subprocess.run stays legal (no life cycle to own)
    return subprocess.run(cmd, capture_output=True, timeout=30)
"""),
        ("rca_tpu/util/procs.py", """\
import subprocess

def spawn_worker(name, argv, env=None):
    # legal ONLY in the procs seam
    return subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, env=env)
"""),
        ("rca_tpu/util/net.py", """\
import socket

def make_server_socket(name, host, port):
    sock = socket.socket()         # legal ONLY in the net seam
    sock.bind((host, port))
    sock.listen(8)
    return sock
"""),
        ("rca_tpu/serve/good_order.py", """\
from rca_tpu.util.threads import make_lock

class Pair:
    def __init__(self):
        self._a = make_lock("Pair._a")
        self._b = make_lock("Pair._b")

    def forward(self):
        with self._a:
            self._inner_b()

    def _inner_b(self):
        with self._b:
            pass

    def also_forward(self):
        with self._a:
            with self._b:          # same order everywhere: acyclic
                pass
"""),
        ("rca_tpu/engine/runner.py", """\
import jax

def timed_fetch(run, timed):
    stacked, diag, vals, idx, n_bad = run()
    return jax.device_get((diag, vals, idx, n_bad))  # audited surface

def full_diagnostics(self):
    return jax.device_get(self._stacked_dev)  # the deferred bulk seam
"""),
        ("rca_tpu/cluster/columnar.py", """\
import numpy as np


def build_view(table):
    \"\"\"[no-dict-scan] assemble the capture view, vectorized.\"\"\"
    feat = table.base.copy()
    feat[:, 0] = table.cpu
    # comprehensions over small registries are the documented allowlist
    lut = np.asarray([table.pos.get(n, -1) for n in table.registry])
    return feat, lut


def encode_row(pod):
    # unmarked row-write encoder: loops are its job (paid per mutation)
    total = 0
    for cs in pod.get("statuses", []):
        total += cs.get("restarts", 0)
    return total
"""),
        ("rca_tpu/serve/good_spans.py", """\
def handle(tracer, ctx, t0, t1):
    with tracer.span("serve.request", parent=ctx) as sp:
        sp.set_attr("tenant", "t")
    # cross-method phases use complete timestamps: cannot leak
    tracer.record("serve.queue", t0, t1, parent=ctx)
    tracer.event("serve.steal", t1, parent=ctx)
"""),
        ("rca_tpu/engine/good_dispatch.py", """\
from rca_tpu.engine.registry import autotune_path, engaged_kernel


def tick(n_pad):
    # the registry IS the seam: asking it is how a surface dispatches
    use_pallas = engaged_kernel(n_pad) == "pallas"
    return use_pallas, autotune_path()
"""),
    )
    result = run_lint(root=root, use_baseline=False)
    assert result.clean, result.findings


def test_static_arg_branching_not_flagged():
    """Regression guard for the taint pass: the real engine branches on
    static_argnames params (use_pallas, error_contrast) inside jit — the
    exact pattern that must stay legal."""
    result = run_lint(
        root=ROOT, rules=["tracer-leak"], use_baseline=False,
        paths=["rca_tpu/engine/runner.py", "rca_tpu/engine/streaming.py",
               "rca_tpu/engine/ell.py"],
    )
    assert result.clean, result.findings


# ---------------------------------------------------------------------------
# suppressions + baseline
# ---------------------------------------------------------------------------

def test_line_suppression(tmp_path):
    rel, src, _ = FIXTURES["env-discipline"]
    src = src.replace(
        'return int(os.environ.get("RCA_PIPELINE_DEPTH", "1"))',
        'return int(os.environ.get("RCA_PIPELINE_DEPTH", "1"))'
        '  # graftlint: disable=env-discipline',
    )
    root = _fake_repo(tmp_path, (rel, src))
    result = run_lint(root=root, rules=["env-discipline"],
                      use_baseline=False)
    assert result.clean
    assert result.suppressed == 1


def test_file_suppression(tmp_path):
    rel, src, _ = FIXTURES["swallowed-faults"]
    src = "# graftlint: disable-file=swallowed-faults\n" + src
    root = _fake_repo(tmp_path, (rel, src))
    result = run_lint(root=root, rules=["swallowed-faults"],
                      use_baseline=False)
    assert result.clean


def test_suppressing_all_rules(tmp_path):
    rel, src, _ = FIXTURES["tick-sync"]
    src = src.replace(
        "jax.device_get(self.x)   # sync outside fetch",
        "jax.device_get(self.x)  # graftlint: disable=all",
    )
    root = _fake_repo(tmp_path, (rel, src))
    result = run_lint(root=root, use_baseline=False)
    assert result.clean


def test_baseline_round_trip(tmp_path):
    rel, src, expected = FIXTURES["rng-key-reuse"]
    root = _fake_repo(tmp_path, (rel, src))
    bpath = str(tmp_path / "baseline.json")

    first = run_lint(root=root, use_baseline=False)
    assert len(first.findings) >= expected
    write_baseline(bpath, first.findings)

    # accepted hits vanish; nothing is stale while the code stands
    second = run_lint(root=root, baseline_path=bpath)
    assert second.clean
    assert second.baselined == len(first.findings)
    assert second.stale_baseline == []

    # fixing the code turns the entries stale (the baseline only shrinks)
    (tmp_path / rel).write_text("""\
import jax

def sample():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return jax.random.normal(k1, (3,)), jax.random.uniform(k2, (3,))
""")
    third = run_lint(root=root, baseline_path=bpath)
    assert third.clean
    assert third.baselined == 0
    assert len(third.stale_baseline) >= 1


def test_baseline_consumed_as_multiset(tmp_path):
    """Two identical flagged lines need two baseline entries — one entry
    must not absorb every future copy of the same bug."""
    rel = "rca_tpu/engine/bad_env.py"
    src = FIXTURES["env-discipline"][1]
    root = _fake_repo(tmp_path, (rel, src))
    bpath = str(tmp_path / "baseline.json")
    write_baseline(bpath, run_lint(root=root, use_baseline=False).findings)

    dup = src + "\n\ndef depth2():\n" \
        "    return int(os.environ.get(\"RCA_PIPELINE_DEPTH\", \"1\"))\n"
    (tmp_path / rel).write_text(dup)
    result = run_lint(root=root, baseline_path=bpath)
    assert len(result.findings) == 1  # the new copy is NOT absorbed


# ---------------------------------------------------------------------------
# repo-wide gates (tier-1)
# ---------------------------------------------------------------------------

def test_repo_is_lint_clean():
    """THE gate: `rca lint` exits 0 on the repo."""
    result = run_lint(root=ROOT)
    assert result.clean, "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in result.findings
    )


def test_baseline_is_empty():
    """Acceptance criterion: every violation the new rules found was
    FIXED, not baselined."""
    assert load_baseline(default_baseline_path(ROOT)) == []


def test_all_eighteen_rules_registered():
    assert set(all_rules()) == {
        "tick-sync", "swallowed-faults", "tracer-leak", "retrace-hazard",
        "rng-key-reuse", "lock-discipline", "env-discipline",
        "nondet-discipline", "resident-fetch", "race-guard",
        "lock-order", "thread-discipline", "no-dict-scan",
        "span-discipline", "kernel-dispatch",
        # graftspec (ISSUE 19)
        "shape-contract", "dtype-discipline", "donation-guard",
    }
    for rule in all_rules().values():
        assert rule.summary and rule.why


def test_nondet_seams_stay_legal(tmp_path):
    """The injectable seams the rule documents must NOT fire: a clock
    function passed as a default parameter (reference, not call), seeded
    random.Random / default_rng construction, and self._clock() timing."""
    root = _fake_repo(tmp_path, ("rca_tpu/serve/good_nondet.py", """\
import random
import time

import numpy as np


class Worker:
    def __init__(self, clock=time.monotonic, seed=0):
        self._clock = clock
        self._rng = random.Random(seed)
        self._np = np.random.default_rng(seed)

    def stamp(self):
        return self._clock()
"""))
    result = run_lint(root=root, rules=["nondet-discipline"],
                      use_baseline=False)
    assert result.clean, result.findings


def test_nondet_allowlist_covers_documented_seams():
    """The shipped allowlist entries are the two documented wall seams —
    running the rule over those exact files stays clean, and removing the
    allowlist in-memory makes them fire (the allowlist is load-bearing,
    not decorative)."""
    paths = ["rca_tpu/cluster/mock_client.py",
             "rca_tpu/replay/recorder.py"]
    result = run_lint(root=ROOT, rules=["nondet-discipline"],
                      use_baseline=False, paths=paths)
    assert result.clean, result.findings

    rule = all_rules()["nondet-discipline"]
    saved = rule.allow
    try:
        rule.allow = {}
        bare = run_lint(root=ROOT, rules=["nondet-discipline"],
                        use_baseline=False, paths=paths)
        assert len(bare.findings) >= 2  # the seams exist and are fenced
    finally:
        rule.allow = saved


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def test_cli_json_and_exit_codes(tmp_path):
    from rca_tpu.analysis.__main__ import main

    rel, src, _ = FIXTURES["env-discipline"]
    root = _fake_repo(tmp_path, (rel, src))
    # findings -> 1; clean subset -> 0; unknown rule -> 2
    assert main(["--root", root, "--no-baseline"]) == 1
    assert main(["--root", root, "--no-baseline",
                 "--rules", "tick-sync"]) == 0
    assert main(["--root", root, "--rules", "no-such-rule"]) == 2


def test_cli_json_shape(tmp_path, capsys):
    from rca_tpu.analysis.__main__ import main

    rel, src, _ = FIXTURES["swallowed-faults"]
    root = _fake_repo(tmp_path, (rel, src))
    rc = main(["--root", root, "--no-baseline", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["clean"] is False
    f = out["findings"][0]
    assert {"rule", "path", "line", "message", "snippet",
            "fingerprint"} <= set(f)


def test_rca_lint_subcommand_forwards():
    from rca_tpu.cli import main

    assert main(["lint", "--list-rules"]) == 0


def test_shims_keep_their_contract():
    """The PR-1/PR-2 scripts still run standalone with the same clean
    message (their tier-1 gates in test_resilience / test_tick_pipeline
    invoke them exactly like this)."""
    for script, marker in (
        ("lint_tick_sync.py", "lint_tick_sync: clean"),
        ("lint_swallowed_faults.py", "lint_swallowed_faults: clean"),
    ):
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", script)],
            capture_output=True, text=True, cwd="/",
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert marker in proc.stdout


# ---------------------------------------------------------------------------
# dynamic companion: recompile gate
# ---------------------------------------------------------------------------

def test_tracecheck_entry_points_compile_once():
    from rca_tpu.analysis import run_tracecheck

    summary = run_tracecheck()
    assert summary["ok"], summary
    names = {e["entry"] for e in summary["entries"]}
    assert {"engine.analyze_case", "engine.analyze_batch",
            "streaming.tick", "propagate_jit"} <= names
    for e in summary["entries"]:
        assert e["recompiles"] == 0, e


def test_tracecheck_detects_a_recompile():
    """The gate actually gates: a function whose cache key changes every
    call (fresh shape) must be reported."""
    import numpy as np

    from rca_tpu.analysis.tracecheck import compile_log_capture

    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x * 2

    sizes = iter([8, 16])

    records = []
    f(jnp.zeros(next(sizes)))  # warm
    with compile_log_capture(records):
        f(jnp.zeros(next(sizes)))  # different shape: must compile
    assert len(records) >= 1
    assert np.all([r.startswith("Compiling") for r in records])
