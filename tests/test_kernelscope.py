"""kernelscope + kernel registry (ISSUE 12): the per-shape kernel table
is the one dispatch seam (rows, autotune cache round trip, cost
analysis), the recompile watchdog flags repeat-signature compiles and
stays silent on clean paths (60-tick chaos soak at depth 2 included),
the device-memory leak gate judges monotonic growth, and the telemetry
reaches /metrics, tick health, the serve summary, and `rca kernels`."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from rca_tpu.engine import registry as reg_mod
from rca_tpu.engine.registry import (
    KernelRegistry,
    autotune_path,
    engaged_kernel,
    kernel_set_hash,
    kernel_table,
    reset_registry,
)
from rca_tpu.observability.kernelscope import (
    DeviceMemoryAccountant,
    RecompileMonitor,
    leak_gate,
    sample_device_memory,
)


@pytest.fixture(autouse=True)
def _fresh_registry(monkeypatch):
    # rows are keyed by the RCA_PALLAS flag, but tests still start from
    # a clean table so ordering cannot leak between them; the default
    # file cache is disabled so no test writes under ~/.cache
    monkeypatch.setenv("RCA_KERNEL_CACHE", "0")
    reset_registry()
    yield
    reset_registry()


# ---------------------------------------------------------------------------
# registry rows
# ---------------------------------------------------------------------------

def test_cpu_rows_default_to_xla_without_timing():
    row = reg_mod.get_registry().resolve(128)
    assert row.winner == "xla"
    assert row.source == "cpu-default"
    assert row.timings_ms == {}  # no interpreter timing on CPU hosts


def test_forced_flag_and_sharded_rows(monkeypatch):
    # RCA_PALLAS=0 marks pallas ineligible (the row records why); the
    # CPU short-circuit still decides the winner
    monkeypatch.setenv("RCA_PALLAS", "0")
    row = reg_mod.get_registry().resolve(1024)
    assert (row.winner, row.source) == ("xla", "cpu-default")
    assert row.eligible["pallas"] == "RCA_PALLAS=0"
    sharded = reg_mod.get_registry().resolve(2048, sharded=True)
    assert (sharded.winner, sharded.source) == ("xla", "sharded")
    assert "shard_map" in sharded.eligible["pallas"]
    assert "shard_map" in sharded.eligible["quantized"]
    assert "shard_map" in sharded.eligible["doubling"]


def test_grown_kernel_set_and_forced_rows(monkeypatch):
    """ISSUE 13 acceptance: KERNELS has >= 5 members; RCA_KERNEL forces
    any of them per shape (eligibility permitting), and the row records
    WHY an ineligible candidate never raced."""
    assert len(reg_mod.KERNELS) >= 5
    assert {"xla", "pallas", "segscan", "quantized", "doubling"} <= set(
        reg_mod.KERNELS
    )
    monkeypatch.setenv("RCA_KERNEL", "quantized")
    row = reg_mod.get_registry().resolve(1024, e_pad=2048)
    assert (row.winner, row.source) == ("quantized", "forced")
    monkeypatch.setenv("RCA_KERNEL", "doubling")
    row = reg_mod.get_registry().resolve(1024, e_pad=2048)
    assert (row.winner, row.source) == ("doubling", "forced")
    monkeypatch.setenv("RCA_KERNEL", "segscan")
    row = reg_mod.get_registry().resolve(1024, e_pad=2048)
    assert (row.winner, row.source) == ("segscan", "forced")
    # ineligible force: segscan needs a 128-divisible edge tier
    row = reg_mod.get_registry().resolve(64, e_pad=64)
    assert (row.winner, row.source) == ("xla", "ineligible")
    assert "128" in row.eligible["segscan"]
    # without an edge tier, edge-layout kernels cannot race
    monkeypatch.delenv("RCA_KERNEL")
    row = reg_mod.get_registry().resolve(512)
    assert "e_pad" in row.eligible["segscan"]
    assert "e_pad" in row.eligible["quantized"]


def test_legacy_segscan_knobs_map_to_registry(monkeypatch):
    """RCA_SEGSCAN=1 / SEGSCAN_INTERPRET=1 force the segscan row;
    RCA_SEGSCAN=0 records ineligibility (knob unification, ISSUE 13)."""
    monkeypatch.setenv("SEGSCAN_INTERPRET", "1")
    row = reg_mod.get_registry().resolve(512, e_pad=512)
    assert (row.winner, row.source) == ("segscan", "forced")
    monkeypatch.setenv("RCA_SEGSCAN", "0")
    row = reg_mod.get_registry().resolve(512, e_pad=512)
    assert row.winner == "xla"
    assert row.eligible["segscan"] == "RCA_SEGSCAN=0"
    monkeypatch.setenv("RCA_SEGSCAN", "1")
    row = reg_mod.get_registry().resolve(512, e_pad=512)
    assert (row.winner, row.source) == ("segscan", "forced")


def test_engaged_kernel_matches_table_by_construction():
    for n_pad in (64, 256, 2048):
        engaged_kernel(n_pad)
    rows = {r["n_pad"]: r["winner"] for r in kernel_table()
            if r["variant"] == "dense"}
    for n_pad, winner in rows.items():
        assert engaged_kernel(n_pad) == winner


def test_autotune_shims_delegate_to_registry():
    from rca_tpu.engine import pallas_kernels as pk

    assert autotune_path() == "xla"            # CPU short-circuit
    assert pk.noisyor_autotune() == "xla"      # legacy shim
    assert pk.noisyor_path() == "xla"


def test_cost_analysis_captured_at_compile_time():
    reg = reg_mod.get_registry()
    row = reg.ensure_cost(reg.resolve(64))
    assert row.cost is not None
    assert row.cost["flops"] > 0
    assert row.cost["bytes_accessed"] > 0
    assert row.cost["peak_temp_bytes"] > 0
    assert row.cost["output_bytes"] > 0


def test_table_cost_cap_bounds_compiles():
    reg = reg_mod.get_registry()
    reg.resolve(64)
    reg.resolve(8192)
    rows = {r["n_pad"]: r for r in reg.table(ensure_cost=True,
                                             cost_max_pad=128)}
    assert rows[64]["cost"] is not None
    assert rows[8192]["cost"] is None  # above the cap: winner only


# ---------------------------------------------------------------------------
# autotune file cache: round trip, corrupt, stale, disabled
# ---------------------------------------------------------------------------

def _accelerated(monkeypatch, timings):
    """Pretend this host is an accelerator so the timed path runs."""
    from rca_tpu.engine import pallas_kernels as pk

    monkeypatch.setattr(reg_mod, "_backend", lambda: "tpu")
    monkeypatch.setattr(pk, "pallas_supported", lambda: True)
    calls = {"n": 0}

    def fake_time(n_pad, e_pad, steps, candidates):
        calls["n"] += 1
        return {k: v for k, v in timings.items() if k in candidates}

    monkeypatch.setattr(reg_mod, "_time_candidates", fake_time)
    return calls


def test_timed_winner_persists_and_reloads(tmp_path, monkeypatch):
    cache = str(tmp_path / "kernels.json")
    calls = _accelerated(monkeypatch, {"xla": 1.0, "pallas": 0.5})
    reg = KernelRegistry(cache_path=cache)
    row = reg.resolve(1024)
    assert (row.winner, row.source) == ("pallas", "timed")
    assert calls["n"] == 1
    assert os.path.exists(cache)
    # a fresh registry (a restart) reads the cache instead of re-timing
    reg2 = KernelRegistry(cache_path=cache)
    row2 = reg2.resolve(1024)
    assert (row2.winner, row2.source) == ("pallas", "cache")
    assert row2.timings_ms == {"xla": 1.0, "pallas": 0.5}
    assert calls["n"] == 1  # no second timing


def test_ties_and_unmeasurable_candidates_go_to_xla(tmp_path, monkeypatch):
    _accelerated(monkeypatch, {"xla": 1.0, "pallas": 0.99})
    reg = KernelRegistry(cache_path=str(tmp_path / "k.json"))
    assert reg.resolve(1024).winner == "xla"   # within 5%: tie → xla
    _accelerated(monkeypatch, {"xla": 1.0, "pallas": None})
    reg2 = KernelRegistry(cache_path=None)
    assert reg2.resolve(2048).winner == "xla"  # cannot time → cannot win


def test_corrupt_cache_retimes_instead_of_crashing(tmp_path, monkeypatch):
    cache = tmp_path / "kernels.json"
    cache.write_text("{not json at all")
    calls = _accelerated(monkeypatch, {"xla": 1.0, "pallas": 0.5})
    reg = KernelRegistry(cache_path=str(cache))
    row = reg.resolve(1024)
    assert (row.winner, row.source) == ("pallas", "timed")
    assert calls["n"] == 1
    # and the rewrite leaves a VALID cache behind
    data = json.loads(cache.read_text())
    assert data["kernel_set"] == kernel_set_hash()


def test_stale_cache_header_retimes(tmp_path, monkeypatch):
    import jax

    cache = tmp_path / "kernels.json"
    cache.write_text(json.dumps({
        "version": 1, "jax": jax.__version__,
        "kernel_set": "deadbeef00000000",   # a different kernel set
        "rows": {"dense:1024:tpu": {"winner": "pallas",
                                    "timings_ms": {}}},
    }))
    calls = _accelerated(monkeypatch, {"xla": 0.4, "pallas": 1.0})
    reg = KernelRegistry(cache_path=str(cache))
    row = reg.resolve(1024)
    # the stale pallas verdict was ignored; fresh timing picked xla
    assert (row.winner, row.source) == ("xla", "timed")
    assert calls["n"] == 1


def test_cache_disabled_writes_nothing(tmp_path, monkeypatch):
    _accelerated(monkeypatch, {"xla": 1.0, "pallas": 0.5})
    reg = KernelRegistry(cache_path=None)
    assert reg.resolve(1024).source == "timed"
    assert list(tmp_path.iterdir()) == []


def test_kernel_cache_path_accessor(monkeypatch):
    from rca_tpu.config import kernel_cache_path

    monkeypatch.setenv("RCA_KERNEL_CACHE", "0")
    assert kernel_cache_path() is None
    monkeypatch.setenv("RCA_KERNEL_CACHE", "off")
    assert kernel_cache_path() is None
    monkeypatch.setenv("RCA_KERNEL_CACHE", "/tmp/x.json")
    assert kernel_cache_path() == "/tmp/x.json"
    monkeypatch.delenv("RCA_KERNEL_CACHE")
    # the default is PLATFORM-KEYED (ISSUE 17): a CPU host and a TPU
    # host must never overwrite each other's timed winners
    from rca_tpu.config import kernel_platform

    assert kernel_cache_path().endswith(
        f"kernel_cache.{kernel_platform()}.json"
    )


# ---------------------------------------------------------------------------
# recompile watchdog
# ---------------------------------------------------------------------------

def test_monitor_clean_path_counts_zero_recompiles():
    import jax
    import jax.numpy as jnp

    with RecompileMonitor(enabled=True) as mon:
        @jax.jit
        def f(x):
            return x * 2.0

        f(jnp.ones(4))
        f(jnp.ones(4))          # jit cache hit: no compile event
        mon.mark_warm()
        f(jnp.ones(16))         # fresh shape tier: fresh, NOT a recompile
        snap = mon.snapshot()
    assert snap["recompiles"] == 0
    assert snap["recompiles_post_warm"] == 0
    assert snap["compiles"] >= 1


def test_monitor_flags_retrace_hazardous_fixture():
    import jax
    import jax.numpy as jnp

    def hazardous(x):
        # a fresh jit wrapper per call: same signature compiled twice —
        # the cache-key-drift class tracecheck's 2-call probe models
        return jax.jit(lambda v: v * 3.0)(x)

    with RecompileMonitor(enabled=True) as mon:
        hazardous(jnp.ones(4))
        mon.mark_warm()
        hazardous(jnp.ones(4))
        hazardous(jnp.ones(4))
        snap = mon.snapshot()
    assert snap["recompiles"] >= 2
    assert snap["recompiles_post_warm"] >= 2
    assert "<lambda>" in snap["recompiled"]


def test_monitor_ignores_scalar_constant_compiles():
    import jax.numpy as jnp

    with RecompileMonitor(enabled=True) as mon:
        # eager constant creation logs identical scalar-only signatures
        # for DIFFERENT output shapes (statics are elided from the log);
        # they must not read as recompiles
        jnp.ones(3)
        jnp.ones(5)
        jnp.ones(7)
        snap = mon.snapshot()
    assert snap["recompiles"] == 0


def test_monitor_disabled_is_a_noop(monkeypatch):
    monkeypatch.setenv("RCA_KERNELSCOPE", "0")
    mon = RecompileMonitor().start()
    assert mon.snapshot() == {
        "enabled": False, "compiles": 0, "recompiles": 0,
        "recompiles_post_warm": 0, "recompiled": [],
    }
    mon.stop()


# ---------------------------------------------------------------------------
# device-memory accountant + leak gate
# ---------------------------------------------------------------------------

def test_sample_device_memory_sees_live_buffers():
    import jax.numpy as jnp

    held = jnp.ones((4096,), jnp.float32) * 2.0
    sample = sample_device_memory()
    assert sample["live_buffers"] >= 1
    assert sample["live_bytes"] >= held.nbytes
    assert sample["bytes_in_use"] >= 0


def test_leak_gate_semantics():
    assert leak_gate([100, 200, 150, 150])["ok"]          # dips: fine
    assert leak_gate([100, 100, 100, 100])["ok"]          # flat: fine
    bad = leak_gate([0, 1 << 21, 1 << 22, 1 << 23])
    assert not bad["ok"] and bad["monotonic_growth"]
    # monotonic but within slack: a plateau with rounding noise passes
    assert leak_gate([100, 101, 102, 103])["ok"]
    assert leak_gate([5, 6])["ok"]                        # too few samples


def test_accountant_cadence_and_gate():
    acc = DeviceMemoryAccountant(sample_every=3, enabled=True)
    for tick in range(1, 10):
        acc.maybe_sample(tick)
    assert acc.samples_taken == 3          # ticks 3, 6, 9
    assert acc.gate()["ok"]


# ---------------------------------------------------------------------------
# integration: chaos soak, serve plane, /metrics, health records, CLI
# ---------------------------------------------------------------------------

def test_chaos_soak_60_ticks_depth2_zero_recompiles_and_memory_gate():
    """ISSUE 12 acceptance: the watchdog reports ZERO post-warmup
    recompiles across a 60-tick chaos soak at pipeline depth 2 (the
    drift tracecheck's 2-call probe cannot see), and the device-memory
    leak gate passes."""
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.resilience.chaos import ChaosConfig, run_chaos_soak

    summary = run_chaos_soak(
        lambda: synthetic_cascade_world(20, n_roots=1, seed=11),
        "synthetic", seed=11, ticks=60, k=5,
        config=ChaosConfig(seed=11), pipeline_depth=2,
    )
    assert summary["uncaught_exceptions"] == 0
    scope = summary["kernelscope"]
    assert scope["enabled"]
    assert scope["recompiles_post_warm"] == 0, scope
    assert scope["memory_samples"] >= 3
    assert scope["memory_gate"]["ok"], scope["memory_gate"]


def test_tick_health_carries_kernelscope(monkeypatch):
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession

    monkeypatch.setenv("RCA_MEM_SAMPLE_EVERY", "1")
    live = LiveStreamingSession(
        MockClusterClient(synthetic_cascade_world(10, n_roots=1, seed=3)),
        "synthetic", k=3,
    )
    out = live.poll()
    scope = out["health"]["kernelscope"]
    assert scope["recompiles"] == 0
    assert scope["compiles"] >= 0
    assert scope["device_memory"]["live_buffers"] >= 1


def test_serve_loop_kernelscope_summary():
    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.config import ServeConfig
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.serve import ServeClient, ServeLoop

    case = synthetic_cascade_arrays(24, n_roots=1, seed=0)
    loop = ServeLoop(engine=GraphEngine(),
                     config=ServeConfig(max_batch=4), kernelscope=True)
    with loop:
        client = ServeClient(loop)
        resp = client.submit(case.features, case.dep_src, case.dep_dst,
                             names=case.names, k=3).result(120.0)
        assert resp.ok
        scope = loop.kernelscope_summary()
    assert scope["enabled"]
    assert scope["recompiles"] == 0
    assert scope["device_memory"]["bytes_in_use"] >= 0
    # the served shape's registry row is in the table the summary exports
    pads = {r["n_pad"] for r in scope["kernel_registry"]}
    assert any(p >= 24 for p in pads)


def test_serve_loop_kernelscope_disabled():
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.serve import ServeLoop

    loop = ServeLoop(engine=GraphEngine(), kernelscope=False)
    with loop:
        scope = loop.kernelscope_summary()
    assert not scope["enabled"]
    assert scope["device_memory"] is None


def test_metrics_exposition_renders_kernelscope():
    from rca_tpu.gateway.export import render_metrics_text

    text = render_metrics_text(
        {"tenants": {}},
        kernelscope={
            "enabled": True, "compiles": 7, "recompiles": 1,
            "device_memory": {
                "bytes_in_use": 4096, "live_buffers": 3,
                "devices": {"0": {"bytes_in_use": 4096,
                                  "peak_bytes_in_use": 8192}},
            },
            "kernel_registry": [{
                "variant": "dense", "n_pad": 128, "backend": "cpu",
                "winner": "xla", "source": "cpu-default",
                "cost": {"flops": 38750.0, "bytes_accessed": 92510.0,
                         "peak_temp_bytes": 5168},
            }],
        },
        now_ms=1234,
    )
    assert "rca_recompiles_total 1" in text
    assert "rca_compiles_total 7" in text
    assert 'rca_device_bytes_in_use{device="0"} 4096 1234' in text
    assert ('rca_kernel_winner_info{kernel="xla",n_pad="128",'
            'source="cpu-default",variant="dense"} 1 1234') in text
    assert ('rca_kernel_cost_flops{n_pad="128",variant="dense"} '
            "38750.0 1234") in text
    assert ('rca_kernel_peak_temp_bytes{n_pad="128",variant="dense"} '
            "5168 1234") in text


def test_kernels_cli_table_and_json(capsys):
    from rca_tpu.cli import main as cli_main

    rc = cli_main(["kernels", "--services", "30", "--no-cost"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "n_pad" in out and "winner" in out and "xla" in out
    rc = cli_main(["kernels", "--services", "30", "--json", "--compact"])
    assert rc == 0
    rows = json.loads(capsys.readouterr().out)["rows"]
    assert any(r["n_pad"] >= 30 and r["winner"] == "xla" for r in rows)


def test_kernels_cli_cost_capture(capsys):
    from rca_tpu.cli import main as cli_main

    rc = cli_main(["kernels", "--services", "20", "--json", "--compact"])
    assert rc == 0
    rows = json.loads(capsys.readouterr().out)["rows"]
    small = [r for r in rows if r["n_pad"] <= 4096 and r["cost"]]
    assert small and small[0]["cost"]["flops"] > 0


# ---------------------------------------------------------------------------
# bench_guard (CI/tooling satellite)
# ---------------------------------------------------------------------------

def _guard():
    import importlib
    import sys

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        return importlib.import_module("bench_guard")
    finally:
        sys.path.remove(tools)


GOOD_LINE = {
    "tick_ms_10k": 10.0,
    "serve_throughput_2k": {"request_ms_p50": 70.0},
    "live_sweep_capture_ms_10k": 80.0,
}


def test_bench_guard_passes_within_threshold(tmp_path):
    bg = _guard()
    current = {**GOOD_LINE, "tick_ms_10k": 11.0}   # +10% < 15%
    report = bg.compare(current, GOOD_LINE)
    assert report["ok"]
    assert report["metrics"]["tick_ms_10k"]["status"] == "ok"


def test_bench_guard_fails_on_regression():
    bg = _guard()
    current = {**GOOD_LINE,
               "serve_throughput_2k": {"request_ms_p50": 90.0}}  # +28%
    report = bg.compare(current, GOOD_LINE)
    assert not report["ok"]
    rec = report["metrics"]["serve_request_ms_p50"]
    assert rec["status"] == "regressed" and rec["change_pct"] > 15


def test_bench_guard_skips_missing_metrics():
    bg = _guard()
    report = bg.compare({"tick_ms_10k": 10.0}, {"tick_ms_10k": 10.0})
    assert report["ok"]
    assert (report["metrics"]["serve_request_ms_p50"]["status"]
            == "skipped")


def test_bench_guard_picks_highest_round_and_unwraps(tmp_path):
    bg = _guard()
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(GOOD_LINE))
    (tmp_path / "BENCH_r11.json").write_text(json.dumps(
        {"cmd": "x", "rc": 0,
         "parsed": {**GOOD_LINE, "tick_ms_10k": 20.0}}
    ))
    (tmp_path / "BENCH_r12.json").write_text("{corrupt")  # skipped
    name, baseline = bg.latest_baseline(str(tmp_path))
    assert name == "BENCH_r11.json"
    assert baseline["tick_ms_10k"] == 20.0


def test_bench_guard_main_exit_codes(tmp_path):
    bg = _guard()
    cur = tmp_path / "line.json"
    cur.write_text(json.dumps({**GOOD_LINE, "tick_ms_10k": 30.0}))
    base = tmp_path / "BENCH_r01.json"
    base.write_text(json.dumps(GOOD_LINE))
    assert bg.main([str(cur), "--baseline", str(base)]) == 1  # 3x tick
    cur.write_text(json.dumps(GOOD_LINE))
    assert bg.main([str(cur), "--baseline", str(base)]) == 0
    # no baseline found: informational pass, never a failure
    assert bg.main([str(cur), "--root", str(tmp_path / "empty")]) == 0
    # unreadable current line: usage error
    assert bg.main([str(tmp_path / "missing.json")]) == 2
