"""ISSUE 8: the multi-replica, multi-device serving plane.

Covers the serve-pool contracts:

- config: ``RCA_SERVE_REPLICAS`` / ``RCA_SERVE_STEAL`` /
  ``RCA_SERVE_REPLICA_MIX`` validation round trips, replica-mix parsing,
  device-group carving;
- partition rules: the declarative table resolves every staged graph
  tensor to the spec the hand-built code used, and unmatched names fail
  loudly;
- routing policy (fake clock, stub devices): home stickiness, resident
  (prepared-graph) stickiness, least-occupied placement for cold
  buckets;
- failover: replica kill recovers with every request answered-or-shed
  and ZERO double completions (staged work stolen, the orphaned
  in-flight batch claimed-and-fetched exactly once); an open breaker
  hands staged work to survivors; stealing disabled rides the
  degradation ladder instead of hanging;
- pool-vs-solo coalesced bit parity on the real engine, including the
  pooled selftest and its kill-replica chaos mode;
- an 8-thread pool stress under ``RCA_RSAN=1`` so gravelock's runtime
  cross-check covers the new thread/lock family (route lock, replica
  locks, completion sink).
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.config import ServeConfig, parse_replica_mix
from rca_tpu.engine import GraphEngine
from rca_tpu.serve import ServeClient, ServePool, ServeRequest, serve_selftest


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _req(tenant="t", n=8, k=3, seed=0, **kw) -> ServeRequest:
    rng = np.random.default_rng(seed)
    feats = rng.uniform(0, 1, (n, 4)).astype(np.float32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    return ServeRequest(
        tenant=tenant, features=feats, dep_src=src, dep_dst=dst, k=k, **kw
    )


class StubHandle:
    def __init__(self, requests, dispatched_at):
        self.requests = requests
        self.dispatched_at = dispatched_at


class StubResult:
    def __init__(self, tag):
        self.ranked = [{"component": f"svc-{tag}", "score": 1.0}]
        self.engine = "stub"
        self.score = np.ones(1, np.float32)


class StubDispatcher:
    """Device-free dispatcher with scriptable failures + a scriptable
    prepared-graph cache (resident stickiness)."""

    engine = None
    engine_tag = "stub"

    def __init__(self):
        self.dispatched = []   # batch widths in dispatch order
        self.fail_next = []    # ops to fail, consumed front-first
        self.graphs = set()    # keys has_graph answers True for

    def has_graph(self, key):
        return key in self.graphs

    def dispatch(self, batch, now=None):
        if self.fail_next and self.fail_next[0] == "dispatch":
            self.fail_next.pop(0)
            raise RuntimeError("injected dispatch failure")
        self.dispatched.append(len(batch))
        self.graphs.add(batch[0].graph_key)
        return StubHandle(list(batch), now if now is not None else 0.0)

    def fetch(self, handle):
        if self.fail_next and self.fail_next[0] == "fetch":
            self.fail_next.pop(0)
            raise RuntimeError("injected fetch failure")
        return [StubResult(i) for i, _ in enumerate(handle.requests)]


def _policy_pool(n=2, clock=None, **cfg_kw):
    """Single-threaded pool over stub dispatchers (never start()ed)."""
    clock = clock or FakeClock()
    cfg_kw.setdefault("max_wait_us", 0)
    stubs = [StubDispatcher() for _ in range(n)]
    pool = ServePool(
        dispatchers=stubs,
        config=ServeConfig(replicas=n, **cfg_kw),
        clock=clock,
    )
    return pool, stubs, clock


def _drain(pool, iters=10):
    for _ in range(iters):
        pool.run_once()


# -- config (satellite: new RCA_SERVE_* knobs) --------------------------------

def test_pool_config_env_round_trip(monkeypatch):
    monkeypatch.setenv("RCA_SERVE_REPLICAS", "4")
    monkeypatch.setenv("RCA_SERVE_STEAL", "0")
    monkeypatch.setenv("RCA_SERVE_REPLICA_MIX", "dense:2,sharded@4:2")
    cfg = ServeConfig.from_env()
    assert cfg.replicas == 4
    assert cfg.steal is False
    assert cfg.replica_specs() == (
        ("dense", None), ("dense", None),
        ("sharded", 4), ("sharded", 4),
    )


def test_pool_config_defaults(monkeypatch):
    for name in ("RCA_SERVE_REPLICAS", "RCA_SERVE_STEAL",
                 "RCA_SERVE_REPLICA_MIX"):
        monkeypatch.delenv(name, raising=False)
    cfg = ServeConfig.from_env()
    assert cfg.replicas == 1 and cfg.steal is True
    assert cfg.replica_specs() == (("dense", None),)


@pytest.mark.parametrize("name,bad", [
    ("RCA_SERVE_REPLICAS", "0"),
    ("RCA_SERVE_REPLICAS", "65"),
    ("RCA_SERVE_REPLICAS", "abc"),
    ("RCA_SERVE_STEAL", "maybe"),
    ("RCA_SERVE_REPLICA_MIX", "gpu:2"),
    ("RCA_SERVE_REPLICA_MIX", "dense:0"),
    ("RCA_SERVE_REPLICA_MIX", "sharded@0:1"),
])
def test_pool_config_rejects_bad_env(monkeypatch, name, bad):
    monkeypatch.setenv(name, bad)
    with pytest.raises(ValueError):
        ServeConfig.from_env()


def test_parse_replica_mix_shapes():
    assert parse_replica_mix("", 3) == (
        ("dense", None), ("dense", None), ("dense", None),
    )
    assert parse_replica_mix("sharded@2") == (("sharded", 2),)
    assert parse_replica_mix("dense:2, sharded@4:1") == (
        ("dense", None), ("dense", None), ("sharded", 4),
    )
    with pytest.raises(ValueError, match="kind"):
        parse_replica_mix("quantum:2")


def test_carve_device_groups_wraps_when_oversubscribed():
    from rca_tpu.parallel.mesh import carve_device_groups

    devices = ["d0", "d1", "d2"]
    groups = carve_device_groups([1, 2, 2], devices)
    assert groups == [["d0"], ["d1", "d2"], ["d0", "d1"]]
    with pytest.raises(ValueError):
        carve_device_groups([1], [])


# -- partition rules (tentpole: one declarative table) ------------------------

def test_partition_rules_match_hand_built_layout():
    from jax.sharding import PartitionSpec as P

    from rca_tpu.parallel.rules import GRAPH_RULES, match_partition_rules

    specs = match_partition_rules(
        GRAPH_RULES,
        ("features_batch", "src_local", "dn_flags", "up_ends",
         "n_live", "aw", "stack", "scores", "topk_vals"),
    )
    assert specs["features_batch"] == P("dp", "sp", None)
    assert specs["src_local"] == P("sp", None)
    assert specs["dn_flags"] == P("sp", None)
    assert specs["up_ends"] == P("sp", None)
    assert specs["n_live"] == P()
    assert specs["aw"] == P()
    assert specs["stack"] == P("dp", None, "sp")
    assert specs["scores"] == P("dp", "sp")
    assert specs["topk_vals"] == P("dp", None)


def test_partition_rules_batch_axes_substitution():
    from jax.sharding import PartitionSpec as P

    from rca_tpu.parallel.rules import GRAPH_RULES

    assert GRAPH_RULES.spec_for(
        "features_batch", batch_axes=("slice", "dp")
    ) == P(("slice", "dp"), "sp", None)
    assert GRAPH_RULES.mesh_axes() == ("dp", "sp")


def test_partition_rules_unmatched_name_fails_loudly():
    from rca_tpu.parallel.rules import GRAPH_RULES

    with pytest.raises(ValueError, match="no partition rule"):
        GRAPH_RULES.spec_for("mystery_tensor")


# -- routing policy (fake clock, stub devices) --------------------------------

def test_routing_cold_bucket_goes_least_occupied():
    pool, stubs, _ = _policy_pool(n=2)
    # preload replica 0 with a different bucket so it is busier
    for i in range(4):
        pool.submit(_req("a", n=8, seed=i))
    pool.route_once()
    assert pool.replicas[0].occupancy() == 4
    pool.submit(_req("b", n=16, seed=9))   # cold bucket
    pool.route_once()
    assert pool.replicas[1].occupancy() == 1


def test_routing_sticky_home_keeps_bucket_on_replica():
    pool, stubs, _ = _policy_pool(n=2)
    pool.submit(_req("a", n=8, seed=0))
    pool.route_once()
    _drain(pool)
    # the bucket now lives on replica 0 (home + prepared graph); later
    # requests follow it even though replica 1 is emptier
    for i in range(3):
        pool.submit(_req("a", n=8, seed=10 + i))
    pool.route_once()
    assert pool.replicas[0].occupancy() == 3
    assert pool.replicas[1].occupancy() == 0


def test_routing_resident_stickiness_beats_occupancy():
    pool, stubs, _ = _policy_pool(n=2)
    probe = _req("a", n=8, seed=0)
    # replica 1 already holds this graph's prepared state (resident
    # base), e.g. from before its bucket went cold and lost its home
    stubs[1].graphs.add(probe.graph_key)
    pool.submit(probe)
    pool.route_once()
    assert pool.replicas[1].occupancy() == 1


# -- failover -----------------------------------------------------------------

def test_replica_kill_recovers_staged_and_inflight():
    """The satellite's core gate: kill a replica holding BOTH staged and
    in-flight work — every request answered-or-shed, zero double
    completions, steals counted."""
    pool, stubs, _ = _policy_pool(n=2, max_batch=4)
    reqs = [_req("a", n=8, seed=i) for i in range(10)]
    reqs += [_req("b", n=16, seed=i) for i in range(4)]
    for r in reqs:
        pool.submit(r)
    pool.route_once()
    # replica 0 dispatches one 4-wide batch (in flight) and keeps the
    # rest of its bucket staged; then it dies
    pool.replicas[0].run_once()
    assert pool.replicas[0]._inflight is not None
    assert pool.replicas[0].batcher.staged() >= 1
    pool.replicas[0].kill()
    _drain(pool)
    resps = [r.result(timeout=0) for r in reqs]
    assert all(resp.status == "ok" for resp in resps)
    assert pool.sink.double_completions == 0
    m = pool.metrics.summary()
    assert m["replicas"]["0"]["state"] == "dead"
    # replica 1 served its own staged work AND the stolen bucket
    assert m["steals_total"] >= 1
    assert stubs[1].dispatched


def test_replica_kill_before_dispatch_steals_everything():
    pool, stubs, _ = _policy_pool(n=2)
    reqs = [_req("a", n=8, seed=i) for i in range(5)]
    for r in reqs:
        pool.submit(r)
    pool.route_once()
    victim = next(r for r in pool.replicas if r.occupancy())
    victim.kill()
    _drain(pool)
    assert all(r.result(timeout=0).status == "ok" for r in reqs)
    assert pool.sink.double_completions == 0
    assert pool.metrics.summary()["steals_total"] == 5


def test_breaker_open_hands_staged_work_to_survivors():
    pool, stubs, clock = _policy_pool(n=2)
    # three consecutive dispatch failures open replica 0's breaker
    stubs[0].fail_next = ["dispatch", "dispatch", "dispatch"]
    burned = []
    for i in range(3):
        r = _req("a", n=8, seed=i)
        burned.append(r)
        pool.submit(r)
        _drain(pool, iters=2)
    assert pool.replicas[0].breaker.state == "open"
    # those requests rode the ladder (no last-known yet -> error)
    assert {r.result(timeout=0).status for r in burned} == {"error"}
    # new same-bucket traffic must NOT pile onto the open replica
    later = [_req("a", n=8, seed=10 + i) for i in range(4)]
    for r in later:
        pool.submit(r)
    _drain(pool)
    assert all(r.result(timeout=0).status == "ok" for r in later)
    assert stubs[1].dispatched  # the survivor served them


def test_no_steal_rides_degradation_ladder():
    pool, stubs, _ = _policy_pool(n=2, steal=False)
    # seed last-known for bucket "a" via a served request
    first = _req("a", n=8, seed=0)
    pool.submit(first)
    _drain(pool)
    assert first.result(timeout=0).status == "ok"
    # stage more work on the home replica, then kill it
    home = pool.replicas[pool._home[first.graph_key]]
    stale = [_req("a", n=8, seed=10 + i) for i in range(3)]
    for r in stale:
        pool.submit(r)
    pool.route_once()
    assert home.occupancy() == 3
    home.kill()
    _drain(pool)
    # stealing off: the victim's staged work degrades (last-known) —
    # answered, never hung, never re-dispatched
    assert {r.result(timeout=0).status for r in stale} == {"degraded"}
    assert pool.metrics.summary()["steals_total"] == 0
    assert pool.sink.double_completions == 0


def test_all_replicas_down_degrades_instead_of_hanging():
    pool, stubs, _ = _policy_pool(n=2)
    for r in pool.replicas:
        r.kill()
    req = _req("a", n=8, seed=0)
    pool.submit(req)
    _drain(pool)
    assert req.result(timeout=0).status == "error"  # no last-known yet


def test_pool_shutdown_resolves_everything():
    pool, stubs, _ = _policy_pool(n=2, max_wait_us=10_000_000,
                                  max_batch=64)
    reqs = [_req("a", seed=i) for i in range(4)]
    for r in reqs:
        pool.submit(r)
    pool.start()
    pool.stop()
    assert all(r.done() for r in reqs)  # nobody left parked forever


def test_pool_expired_requests_shed_at_every_stage():
    clock = FakeClock()
    pool, stubs, clock = _policy_pool(n=2, clock=clock)
    dead = _req("a", deadline_s=5.0)
    live = _req("a", seed=9, deadline_s=100.0)
    pool.submit(dead)
    pool.submit(live)
    clock.advance(10.0)
    _drain(pool)
    assert dead.result(timeout=0).status == "shed"
    assert live.result(timeout=0).status == "ok"
    assert sum(sum(s.dispatched) for s in stubs) == 1


# -- real engine: pool-vs-solo bit parity ------------------------------------

@pytest.fixture(scope="module")
def engine():
    return GraphEngine()


def test_pool_parity_vs_solo(engine):
    """A request served by ANY replica of the pool is bit-identical to
    the same request analyzed solo (the satellite's parity gate)."""
    case = synthetic_cascade_arrays(60, n_roots=1, seed=3)
    rng = np.random.default_rng(0)
    pool = ServePool(
        engines=[engine, GraphEngine()],
        config=ServeConfig(replicas=2),
    )
    feats = [
        np.clip(case.features + rng.uniform(
            0, 0.05, case.features.shape
        ).astype(np.float32), 0, 1)
        for _ in range(12)
    ]
    with pool:
        client = ServeClient(pool)
        reqs = [
            client.submit(
                f, case.dep_src, case.dep_dst, names=case.names,
                tenant=f"t{i % 3}", k=3,
            )
            for i, f in enumerate(feats)
        ]
        resps = [r.result(120.0) for r in reqs]
    assert all(r.status == "ok" for r in resps)
    for f, resp in zip(feats, resps):
        solo = engine.analyze_arrays(
            f, case.dep_src, case.dep_dst, case.names, k=3,
        )
        assert resp.ranked == solo.ranked
        assert np.array_equal(resp.result.score, solo.score)
    assert pool.sink.double_completions == 0


def test_pool_selftest_contract(engine):
    """The pooled selftest behind ``rca serve --selftest --replicas N``:
    contract + parity + per-replica metric rows."""
    out = serve_selftest(n_requests=24, seed=0, engine=engine, replicas=2)
    assert out["ok"], out
    assert out["all_resolved"] and out["parity_ok"]
    assert out["replicas"] == 2
    assert out["double_completions"] == 0
    assert set(out["metrics"]["replicas"]) == {"0", "1"}
    assert set(out["breaker_state"]) == {"0", "1"}


def test_pool_selftest_kill_replica(engine):
    """Kill-replica chaos through the full threaded stack: recovery
    drops nothing and completion stays exactly-once."""
    out = serve_selftest(
        n_requests=24, seed=1, engine=engine, replicas=2,
        kill_replica=True,
    )
    assert out["ok"], out
    assert out["all_resolved"] and out["parity_ok"]
    assert out["by_status"].get("error", 0) == 0
    assert out["double_completions"] == 0
    assert "dead" in out["breaker_state"].values()


def test_pool_mixed_dense_sharded_parity(engine):
    """A dense+sharded mix serves with per-kind bit parity (sharded
    responses check against the replica's own sharded engine)."""
    out = serve_selftest(
        n_requests=16, seed=0, engine=engine,
        replica_mix="dense:1,sharded@2:1",
    )
    assert out["ok"], out
    assert out["parity_ok"]
    assert out["replica_mix"] == ["dense", "sharded"]


# -- rsan: the new thread/lock family under the runtime sanitizer ------------

def test_pool_stress_under_rsan():
    """Satellite: an 8-thread barrage through a STARTED pool (real
    worker threads + submitters + a mid-run replica kill) with every
    lock sanitized — no observed races, no lock-order contradiction
    against gravelock's static model, and the new locks really were
    contended across threads."""
    from rca_tpu.analysis.concurrency import model_for, rsan
    from rca_tpu.analysis.concurrency.crosscheck import (
        order_contradictions,
    )
    from rca_tpu.analysis.core import repo_root

    was = rsan.enabled()
    rsan.enable()
    rsan.RSAN.reset()
    try:
        stubs = [StubDispatcher() for _ in range(4)]
        pool = ServePool(
            dispatchers=stubs,
            config=ServeConfig(replicas=4, max_wait_us=0),
        )
        reqs = [[] for _ in range(8)]

        def submitter(w: int) -> None:
            for i in range(24):
                r = _req(f"t{w % 3}", n=8 + 8 * (w % 2), seed=w * 100 + i)
                reqs[w].append(r)
                pool.submit(r)
                if w == 0 and i == 12:
                    pool.replicas[0].kill()

        with pool:
            threads = [
                threading.Thread(target=submitter, args=(w,))
                for w in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            resps = [r.result(60.0) for lane in reqs for r in lane]
        assert all(r.status in ("ok", "degraded", "error")
                   for r in resps)
        assert all(r.done() for lane in reqs for r in lane)
        assert pool.sink.double_completions == 0

        assert rsan.RSAN.races_observed() == []
        lt = rsan.RSAN.lock_threads()
        assert len(lt.get("ServePool._route_lock", ())) >= 2
        assert len(lt.get("ReplicaWorker._lock", ())) >= 2
        assert len(lt.get("CompletionSink._lock", ())) >= 2
        static_edges = model_for(repo_root()).static_order_edges()
        assert order_contradictions(
            static_edges, rsan.RSAN.order_edges()
        ) == []
    finally:
        rsan.RSAN.reset()
        if not was:
            rsan.disable()
