"""Signal agents against the faulted 5-service fixture.

Each test asserts the agent surfaces the fixture's injected fault the same
way the reference's rule agents would (reference rule tables: SURVEY.md §2.4).
"""

import numpy as np
import pytest

from rca_tpu.agents import (
    ALL_AGENT_TYPES,
    AnalysisContext,
    make_agents,
)
from rca_tpu.cluster.fixtures import NS, five_service_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot


@pytest.fixture(scope="module")
def ctx():
    client = MockClusterClient(five_service_world())
    return AnalysisContext(ClusterSnapshot.capture(client, NS))


@pytest.fixture(scope="module")
def results(ctx):
    return {name: agent.analyze(ctx) for name, agent in make_agents().items()}


def _components(result, severity=None):
    return [
        f["component"]
        for f in result.findings
        if severity is None or f["severity"] == severity
    ]


def test_all_agents_return_contract(results):
    assert set(results) == set(ALL_AGENT_TYPES)
    for name, res in results.items():
        d = res.to_dict()
        assert d["agent_type"] == name
        assert isinstance(d["findings"], list)
        assert res.reasoning_steps, name
        assert res.summary
        for f in d["findings"]:
            assert set(f) >= {
                "component", "issue", "severity", "evidence",
                "recommendation", "timestamp",
            }


def test_metrics_agent_flags_hot_pods(results):
    comps = _components(results["metrics"])
    assert any("backend" in c for c in comps)          # 95% CPU
    assert any("resource-service" in c for c in comps)  # ~90% memory
    # api-gateway HPA wants 2 replicas but has 1
    assert any(c == "HPA/api-gateway-hpa" for c in comps)


def test_logs_agent_finds_database_errors(results):
    res = results["logs"]
    db = [f for f in res.findings if "database" in f["component"]]
    assert db
    patterns = {f["evidence"].get("pattern") for f in db if isinstance(f["evidence"], dict)}
    assert "exception" in patterns
    # crashloop container-state classification
    assert any("CrashLoopBackOff" in f["issue"] for f in db)
    # example lines extracted from the raw text
    ex = [
        f for f in db
        if isinstance(f["evidence"], dict) and f["evidence"].get("examples")
    ]
    assert ex


def test_events_agent_groups_and_flags_frequency(results):
    res = results["events"]
    # database BackOff event recurs 5 times -> not above the >5 threshold;
    # backend CPUThrottling recurs 10 times -> medium frequency finding
    comps = _components(res)
    assert any("backend" in c for c in comps)


def test_topology_agent_structure(results):
    res = results["topology"]
    comps = _components(res)
    # api-gateway envFrom references a secret that does not exist
    assert any(
        "api-gateway" in f["component"] and "secret" in f["issue"]
        for f in res.findings
    )
    # network policy 'from' selector matches no pods
    assert any("NetworkPolicy/backend-network-policy" in c for c in comps)
    # services whose pods are all unready
    assert any(
        c in ("Service/database", "Service/api-gateway") for c in comps
    )
    assert "graph" in res.data and res.data["graph"]["nodes"]
    mapping = res.data["service_pod_mapping"]
    assert mapping["frontend"]["ready"] == 2
    assert mapping["database"]["ready"] == 0


def test_traces_agent_error_rates_and_latency(results):
    res = results["traces"]
    highs = [
        f for f in res.findings
        if f["severity"] == "high" and "error rate" in f["issue"]
    ]
    assert any("api-gateway" in f["component"] for f in highs)   # 25%
    assert any("database" in f["component"] for f in highs)      # 15%
    # backend p99 2000ms vs median -> degraded
    assert any(
        "backend" in f["component"] and "latency" in f["issue"]
        for f in res.findings
    )


def test_resource_agent_buckets(results):
    res = results["resources"]
    buckets = res.data["pod_buckets"]
    assert buckets["crashloop"] == 1      # database
    assert buckets["failed"] == 1         # api-gateway
    crash = [
        f for f in res.findings
        if f.get("bucket") == "crashloop"
    ]
    assert crash and "database" in crash[0]["component"]
    # deployment ready shortfalls for database and api-gateway
    dep = [
        f["component"] for f in res.findings
        if f["component"].startswith("Deployment/")
    ]
    assert "Deployment/database" in dep
    assert "Deployment/api-gateway" in dep


def test_event_correlation_attaches_related_events(results):
    res = results["resources"]
    db = [
        f for f in res.findings
        if f["component"] == "Pod/database-7c9f8b6d5e-3x5qp"
        and isinstance(f["evidence"], dict)
        and f["evidence"].get("related_events")
    ]
    assert db
    assert any(
        e["reason"] == "BackOff" for e in db[0]["evidence"]["related_events"]
    )


def test_agents_are_stateless(ctx):
    agent = make_agents()["resources"]
    r1 = agent.analyze(ctx)
    r2 = agent.analyze(ctx)
    assert len(r1.findings) == len(r2.findings)
    assert r1.findings is not r2.findings
