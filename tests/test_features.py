"""Feature-extraction tests over the 5-service fixture and synthetic worlds."""

import numpy as np

from rca_tpu.cluster.fixtures import NS
from rca_tpu.cluster.generator import synthetic_cascade_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.features import PodF, SvcF, extract_features, scan_text
from rca_tpu.features.logscan import LOG_PATTERN_NAMES


def _features(client, ns):
    return extract_features(ClusterSnapshot.capture(client, ns))


def test_log_scanner_classes():
    counts = scan_text(
        "ERROR: connection refused\nOOMKilled by kernel\n"
        "request timed out\nTraceback (most recent call last):\n"
    )
    by_name = dict(zip(LOG_PATTERN_NAMES, counts.tolist()))
    assert by_name["connection_refused"] == 1
    assert by_name["oom_kill"] >= 1
    assert by_name["timeout"] == 1
    assert by_name["exception"] >= 2  # ERROR + Traceback
    assert scan_text("").sum() == 0


def test_pod_features_five_service(five_svc_client):
    fs = _features(five_svc_client, NS)
    assert fs.num_pods == 6 and fs.num_services == 5
    idx = {n: i for i, n in enumerate(fs.pod_names)}
    db = fs.pod_features[idx["database-7c9f8b6d5e-3x5qp"]]
    assert db[PodF.WAIT_CRASHLOOP] == 1.0
    assert db[PodF.RESTARTS] == 5.0
    assert db[PodF.TERM_NONZERO] == 1.0
    gw = fs.pod_features[idx["api-gateway-6b7c8d9e5f-4q3zx"]]
    assert gw[PodF.PHASE_FAILED] == 1.0
    be = fs.pod_features[idx["backend-5b6d8f9c7d-2zf8g"]]
    assert be[PodF.CPU_PCT] > 0.9
    # every pod maps to a service
    assert (fs.pod_service >= 0).all()


def test_service_features_five_service(five_svc_client):
    fs = _features(five_svc_client, NS)
    sidx = {n: i for i, n in enumerate(fs.service_names)}
    svc = fs.service_features
    assert svc[sidx["database"], SvcF.CRASH] == 1.0
    assert svc[sidx["api-gateway"], SvcF.CRASH] == 1.0
    assert svc[sidx["frontend"], SvcF.CRASH] == 0.0
    # empty endpoints mark NOT_READY even without pod evidence
    assert svc[sidx["database"], SvcF.NOT_READY] == 1.0
    assert svc[sidx["api-gateway"], SvcF.ERROR_RATE] == 0.25
    assert svc[sidx["backend"], SvcF.RESOURCE] > 0.9
    # backend p99=2000 vs median 600 → elevated latency score
    assert svc[sidx["backend"], SvcF.LATENCY] > 0.3


def test_synthetic_world_features_separate_roots():
    w = synthetic_cascade_world(50, n_roots=2, seed=3)
    client = MockClusterClient(w)
    fs = _features(client, w.ground_truth["namespace"])
    sidx = {n: i for i, n in enumerate(fs.service_names)}
    roots = w.ground_truth["fault_roots"]
    crash = fs.service_features[:, SvcF.CRASH]
    for r in roots:
        assert crash[sidx[r]] == 1.0
    non_root = np.ones(len(fs.service_names), bool)
    for r in roots:
        non_root[sidx[r]] = False
    assert crash[non_root].max() == 0.0


def test_shared_selector_services_both_get_members():
    """One pod backing two services (ClusterIP + headless with the same
    selector) must appear in both memberships — no false 'selector matches
    no pods' findings."""
    from rca_tpu.cluster.world import World, make_pod, make_service
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.features.extract import extract_features

    w = World(cluster_name="t")
    ns = "ns"
    w.add("pods", ns, make_pod("db-0", ns, "db"))
    w.add("services", ns, make_service("db", ns))
    headless = make_service("db-headless", ns)
    headless["spec"]["selector"] = {"app": "db"}
    w.add("services", ns, headless)
    snap = ClusterSnapshot.capture(MockClusterClient(w), ns)
    fs = extract_features(snap)
    for j, name in enumerate(fs.service_names):
        assert len(fs.service_members(j)) == 1, name
    # both services aggregate the pod's features identically
    assert (fs.service_features[0] == fs.service_features[1]).all()


def test_dns_inference_rejects_foreign_namespace():
    from rca_tpu.graph.build import _dns_service_names

    assert _dns_service_names("http://db.prod2.svc:5432", ["db"], "prod1") == set()
    assert _dns_service_names("http://db.prod1.svc:5432", ["db"], "prod1") == {"db"}
    assert _dns_service_names("http://db:5432", ["db"], "prod1") == {"db"}
