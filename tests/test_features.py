"""Feature-extraction tests over the 5-service fixture and synthetic worlds."""

import numpy as np

from rca_tpu.cluster.fixtures import NS
from rca_tpu.cluster.generator import synthetic_cascade_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.features import PodF, SvcF, extract_features, scan_text
from rca_tpu.features.logscan import LOG_PATTERN_NAMES


def _features(client, ns):
    return extract_features(ClusterSnapshot.capture(client, ns))


def test_log_scanner_classes():
    counts = scan_text(
        "ERROR: connection refused\nOOMKilled by kernel\n"
        "request timed out\nTraceback (most recent call last):\n"
    )
    by_name = dict(zip(LOG_PATTERN_NAMES, counts.tolist()))
    assert by_name["connection_refused"] == 1
    assert by_name["oom_kill"] >= 1
    assert by_name["timeout"] == 1
    assert by_name["exception"] >= 2  # ERROR + Traceback
    assert scan_text("").sum() == 0


def test_pod_features_five_service(five_svc_client):
    fs = _features(five_svc_client, NS)
    assert fs.num_pods == 6 and fs.num_services == 5
    idx = {n: i for i, n in enumerate(fs.pod_names)}
    db = fs.pod_features[idx["database-7c9f8b6d5e-3x5qp"]]
    assert db[PodF.WAIT_CRASHLOOP] == 1.0
    assert db[PodF.RESTARTS] == 5.0
    assert db[PodF.TERM_NONZERO] == 1.0
    gw = fs.pod_features[idx["api-gateway-6b7c8d9e5f-4q3zx"]]
    assert gw[PodF.PHASE_FAILED] == 1.0
    be = fs.pod_features[idx["backend-5b6d8f9c7d-2zf8g"]]
    assert be[PodF.CPU_PCT] > 0.9
    # every pod maps to a service
    assert (fs.pod_service >= 0).all()


def test_service_features_five_service(five_svc_client):
    fs = _features(five_svc_client, NS)
    sidx = {n: i for i, n in enumerate(fs.service_names)}
    svc = fs.service_features
    assert svc[sidx["database"], SvcF.CRASH] == 1.0
    assert svc[sidx["api-gateway"], SvcF.CRASH] == 1.0
    assert svc[sidx["frontend"], SvcF.CRASH] == 0.0
    # empty endpoints mark NOT_READY even without pod evidence
    assert svc[sidx["database"], SvcF.NOT_READY] == 1.0
    assert svc[sidx["api-gateway"], SvcF.ERROR_RATE] == 0.25
    assert svc[sidx["backend"], SvcF.RESOURCE] > 0.9
    # backend p99=2000 vs median 600 → elevated latency score
    assert svc[sidx["backend"], SvcF.LATENCY] > 0.3


def test_synthetic_world_features_separate_roots():
    w = synthetic_cascade_world(50, n_roots=2, seed=3)
    client = MockClusterClient(w)
    fs = _features(client, w.ground_truth["namespace"])
    sidx = {n: i for i, n in enumerate(fs.service_names)}
    roots = w.ground_truth["fault_roots"]
    crash = fs.service_features[:, SvcF.CRASH]
    for r in roots:
        assert crash[sidx[r]] == 1.0
    non_root = np.ones(len(fs.service_names), bool)
    for r in roots:
        non_root[sidx[r]] = False
    assert crash[non_root].max() == 0.0


def test_shared_selector_services_both_get_members():
    """One pod backing two services (ClusterIP + headless with the same
    selector) must appear in both memberships — no false 'selector matches
    no pods' findings."""
    from rca_tpu.cluster.world import World, make_pod, make_service
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.features.extract import extract_features

    w = World(cluster_name="t")
    ns = "ns"
    w.add("pods", ns, make_pod("db-0", ns, "db"))
    w.add("services", ns, make_service("db", ns))
    headless = make_service("db-headless", ns)
    headless["spec"]["selector"] = {"app": "db"}
    w.add("services", ns, headless)
    snap = ClusterSnapshot.capture(MockClusterClient(w), ns)
    fs = extract_features(snap)
    for j, name in enumerate(fs.service_names):
        assert len(fs.service_members(j)) == 1, name
    # both services aggregate the pod's features identically
    assert (fs.service_features[0] == fs.service_features[1]).all()


def test_dns_inference_rejects_foreign_namespace():
    from rca_tpu.graph.build import _dns_service_names

    assert _dns_service_names("http://db.prod2.svc:5432", ["db"], "prod1") == set()
    assert _dns_service_names("http://db.prod1.svc:5432", ["db"], "prod1") == {"db"}
    assert _dns_service_names("http://db:5432", ["db"], "prod1") == {"db"}


def test_silent_channel_semantics():
    """Absence evidence (VERDICT r3 item 4): SILENT fires for a service
    that is not-ready with zero crash/restart/log evidence (never started
    — image-pull/unschedulable roots), and stays ~0 for crashing services
    (they provably ran) and for healthy ones."""
    import numpy as np

    from rca_tpu.features.schema import (
        NUM_SERVICE_FEATURES,
        SvcF,
        derive_silent_channel,
    )

    f = np.zeros((4, NUM_SERVICE_FEATURES), np.float32)
    # 0: image-pull root — down, silent
    f[0, SvcF.NOT_READY] = 0.9
    # 1: crash root — down but demonstrably ran
    f[1, SvcF.NOT_READY] = 0.9
    f[1, SvcF.CRASH] = 0.95
    f[1, SvcF.RESTARTS] = 0.8
    # 2: healthy
    # 3: victim — not ready with log errors
    f[3, SvcF.NOT_READY] = 1.0
    f[3, SvcF.LOG_ERRORS] = 0.7
    derive_silent_channel(f)
    s = f[:, SvcF.SILENT]
    assert s[0] > 0.85
    assert s[1] < 0.05
    assert s[2] == 0.0
    assert abs(s[3] - 0.3) < 0.05


def test_silent_channel_in_extractor_and_generator():
    """Both feature producers derive the channel: an ImagePullBackOff
    world-root gets SILENT from the extractor; a generated image-root
    cascade gets it from the generator (and dropout never zeroes it
    independently of its inputs)."""
    import numpy as np

    from rca_tpu.cluster.generator import (
        synthetic_cascade_arrays,
        synthetic_cascade_world,
    )
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.features.extract import extract_features
    from rca_tpu.features.schema import SvcF

    w = synthetic_cascade_world(30, n_roots=1, seed=3, fault_mix="image")
    ns = w.ground_truth["namespace"]
    root = w.ground_truth["fault_roots"][0]
    snap = ClusterSnapshot.capture(MockClusterClient(w), ns)
    fs = extract_features(snap)
    j = fs.service_names.index(root)
    assert fs.service_features[j, SvcF.SILENT] > 0.8
    # healthy services stay ~0
    healthy = [i for i, n in enumerate(fs.service_names) if n != root]
    assert float(np.max(fs.service_features[healthy, SvcF.SILENT])) < 0.3

    case = synthetic_cascade_arrays(300, n_roots=1, seed=5, fault_mix="image")
    r = int(case.roots[0])
    assert case.features[r, SvcF.SILENT] > 0.5


def test_silent_channel_raw_channels_byte_stable():
    """Adding the derived channel must not disturb any pre-existing
    seed's RAW channels (rng draws cover only the raw block)."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.features.schema import NUM_RAW_SERVICE_FEATURES, SvcF

    case = synthetic_cascade_arrays(
        200, n_roots=2, seed=11, mode="adversarial", fault_mix="mixed"
    )
    # pinned spot checks generated by the pre-SILENT (round-3) generator
    # for seed 11 and verified byte-identical at the changeover: the raw
    # block must keep these exact float32 values
    raw = case.features[:, :NUM_RAW_SERVICE_FEATURES]
    assert raw.shape[1] == int(SvcF.SILENT)
    assert np.isfinite(raw).all()
    pinned = {
        (0, 0): 0.031727083,
        (7, 6): 0.04161558,
        (50, 1): 0.2979589,
        (123, 4): 0.08722562,
        (199, 11): 0.024340408,
    }
    for (i, ch), want in pinned.items():
        assert raw[i, ch] == np.float32(want), (i, ch, raw[i, ch])
    assert abs(float(raw.sum()) - 294.13626) < 1e-3
    # derived column is a pure function of the raw block
    expect = (
        np.clip(case.features[:, SvcF.NOT_READY], 0, 1)
        * (1 - np.clip(case.features[:, SvcF.CRASH], 0, 1))
        * (1 - np.clip(case.features[:, SvcF.RESTARTS], 0, 1))
        * (1 - np.clip(case.features[:, SvcF.LOG_ERRORS], 0, 1))
    )
    np.testing.assert_allclose(
        case.features[:, SvcF.SILENT], expect, atol=1e-6
    )
