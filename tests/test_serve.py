"""ISSUE 3: multi-tenant serving scheduler (rca_tpu/serve, SERVING.md).

Covers the serving contracts:

- scheduling policy (fake clock, no device): bounded admission
  (``queue_full``), weighted-fair + priority service order, per-tenant
  FIFO, deadline shedding at every stage — an expired request NEVER
  consumes a device slot;
- shape-bucket flush policy: full batch flushes immediately, the wait
  bound flushes partial groups, an idle engine never sits out the wait
  window, distinct graphs never coalesce;
- resilience: dispatch/fetch failures answer ``degraded`` (last-known
  ranking) or ``error``, the breaker opens and answers without touching
  the device, every request resolves exactly once;
- batching parity: a request served from a coalesced batch is
  bit-identical to the same request served alone, across bucket sizes
  and tenant mixes, including under chaos faults;
- the end-to-end selftest behind ``rca serve --selftest`` (the tier-1
  smoke) and the coordinator's ``serve=`` integration;
- ``RCA_SERVE_*`` env-var validation round trip.
"""

import threading

import numpy as np
import pytest

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.config import ServeConfig
from rca_tpu.engine import GraphEngine
from rca_tpu.serve import (
    PRIORITY_HIGH,
    BatchDispatcher,
    RequestQueue,
    ServeClient,
    ServeLoop,
    ServeRequest,
    ShapeBucketBatcher,
    serve_selftest,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _req(tenant="t", n=8, k=3, seed=0, **kw) -> ServeRequest:
    rng = np.random.default_rng(seed)
    feats = rng.uniform(0, 1, (n, 4)).astype(np.float32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    return ServeRequest(
        tenant=tenant, features=feats, dep_src=src, dep_dst=dst, k=k, **kw
    )


class StubHandle:
    def __init__(self, requests, dispatched_at):
        self.requests = requests
        self.dispatched_at = dispatched_at


class StubResult:
    def __init__(self, tag):
        self.ranked = [{"component": f"svc-{tag}", "score": 1.0}]
        self.engine = "stub"
        self.score = np.ones(1, np.float32)


class StubDispatcher:
    """Device-free dispatcher: records every batch, optional scripted
    failures per op ("dispatch"/"fetch")."""

    engine = None
    engine_tag = "stub"

    def __init__(self):
        self.dispatched = []   # list of batch widths
        self.fail_next = []    # ops to fail, consumed front-first

    def dispatch(self, batch, now=None):
        if self.fail_next and self.fail_next[0] == "dispatch":
            self.fail_next.pop(0)
            raise RuntimeError("injected dispatch failure")
        self.dispatched.append(len(batch))
        return StubHandle(list(batch), now if now is not None else 0.0)

    def fetch(self, handle):
        if self.fail_next and self.fail_next[0] == "fetch":
            self.fail_next.pop(0)
            raise RuntimeError("injected fetch failure")
        return [StubResult(i) for i, _ in enumerate(handle.requests)]


def _policy_loop(clock=None, **cfg_kw):
    """Single-threaded loop over a stub dispatcher (never start()ed)."""
    clock = clock or FakeClock()
    stub = StubDispatcher()
    loop = ServeLoop(
        config=ServeConfig(**cfg_kw), clock=clock, dispatcher=stub,
    )
    return loop, stub, clock


def _drain(loop, iters=10):
    for _ in range(iters):
        loop.run_once()


# -- config (satellite: RCA_SERVE_* validation) ------------------------------

def test_serve_config_env_round_trip(monkeypatch):
    monkeypatch.setenv("RCA_SERVE_MAX_BATCH", "32")
    monkeypatch.setenv("RCA_SERVE_MAX_WAIT_US", "500")
    monkeypatch.setenv("RCA_SERVE_QUEUE_CAP", "77")
    cfg = ServeConfig.from_env()
    assert (cfg.max_batch, cfg.max_wait_us, cfg.queue_cap) == (32, 500, 77)


def test_serve_config_defaults_when_unset(monkeypatch):
    for name in ("RCA_SERVE_MAX_BATCH", "RCA_SERVE_MAX_WAIT_US",
                 "RCA_SERVE_QUEUE_CAP"):
        monkeypatch.delenv(name, raising=False)
    cfg = ServeConfig.from_env()
    assert (cfg.max_batch, cfg.max_wait_us, cfg.queue_cap) == (16, 2000, 256)


@pytest.mark.parametrize("name,bad", [
    ("RCA_SERVE_MAX_BATCH", "0"),
    ("RCA_SERVE_MAX_BATCH", "5000"),
    ("RCA_SERVE_MAX_BATCH", "abc"),
    ("RCA_SERVE_MAX_WAIT_US", "-1"),
    ("RCA_SERVE_QUEUE_CAP", "0"),
])
def test_serve_config_rejects_bad_env(monkeypatch, name, bad):
    monkeypatch.setenv(name, bad)
    with pytest.raises(ValueError, match=name):
        ServeConfig.from_env()


def test_serve_config_rejects_bad_direct_construction():
    with pytest.raises(ValueError):
        ServeConfig(max_batch=0)
    with pytest.raises(ValueError):
        ServeConfig(max_wait_us=-5)
    with pytest.raises(ValueError):
        ServeConfig(queue_cap=0)


# -- queue policy ------------------------------------------------------------

def test_queue_caps_admission():
    q = RequestQueue(cap=2, clock=FakeClock())
    assert q.submit(_req("a"))
    assert q.submit(_req("a"))
    assert not q.submit(_req("b"))  # full: rejected, NOT queued
    assert len(q) == 2


def test_queue_weighted_fair_interleaves_flooding_tenant():
    q = RequestQueue(cap=64, clock=FakeClock())
    for i in range(6):
        q.submit(_req("flood", seed=i))
    for i in range(2):
        q.submit(_req("light", seed=10 + i))
    order = [q.pop().tenant for _ in range(8)]
    # start-time fair queuing: the light tenant's 2 requests interleave
    # with the flood's first two instead of waiting behind all six
    assert order[:4].count("light") == 2


def test_queue_weight_scales_drain_rate():
    q = RequestQueue(cap=64, clock=FakeClock())
    q.set_weight("heavy", 2.0)
    for i in range(8):
        q.submit(_req("heavy", seed=i))
        q.submit(_req("light", seed=100 + i))
    first6 = [q.pop().tenant for _ in range(6)]
    # weight 2 drains twice as fast under contention
    assert first6.count("heavy") == 4
    assert first6.count("light") == 2


def test_queue_priority_pops_before_normal():
    q = RequestQueue(cap=64, clock=FakeClock())
    q.submit(_req("a", seed=1))
    q.submit(_req("a", seed=2))
    q.submit(_req("b", seed=3, priority=PRIORITY_HIGH))
    assert q.pop().tenant == "b"


def test_queue_per_tenant_fifo():
    q = RequestQueue(cap=64, clock=FakeClock())
    reqs = [_req("a", seed=i) for i in range(5)]
    for r in reqs:
        q.submit(r)
    popped = [q.pop().request_id for _ in range(5)]
    assert popped == [r.request_id for r in reqs]


def test_queue_sheds_expired_only():
    clock = FakeClock()
    q = RequestQueue(cap=64, clock=clock)
    q.submit(_req("a", deadline_s=1.0))
    q.submit(_req("a", deadline_s=100.0))
    clock.advance(5.0)
    shed = q.shed_expired()
    assert len(shed) == 1 and shed[0].deadline_s == 1.0
    assert len(q) == 1


# -- batcher flush policy ----------------------------------------------------

def _offer(b, req, now):
    req.enqueued_at = now
    b.offer(req)


def test_batcher_full_batch_flushes_immediately():
    clock = FakeClock()
    b = ShapeBucketBatcher(max_batch=3, max_wait_us=10_000_000, clock=clock)
    for i in range(3):
        _offer(b, _req("a", seed=i), clock())
    batch = b.take_ready()
    assert batch is not None and len(batch) == 3
    assert b.staged() == 0


def test_batcher_partial_waits_then_flushes():
    clock = FakeClock()
    b = ShapeBucketBatcher(max_batch=8, max_wait_us=2000, clock=clock)
    _offer(b, _req("a"), clock())
    assert b.take_ready() is None          # worth holding for batchmates
    clock.advance(0.0021)                  # past the 2000 us wait bound
    batch = b.take_ready()
    assert batch is not None and len(batch) == 1


def test_batcher_drain_skips_wait_window():
    clock = FakeClock()
    b = ShapeBucketBatcher(max_batch=8, max_wait_us=10_000_000, clock=clock)
    _offer(b, _req("a"), clock())
    # idle engine (drain): a lone request's latency is one dispatch,
    # not max_wait plus one
    assert b.take_ready(drain=True) is not None


def test_batcher_never_mixes_graphs():
    clock = FakeClock()
    b = ShapeBucketBatcher(max_batch=8, max_wait_us=0, clock=clock)
    _offer(b, _req("a", n=8), clock())
    _offer(b, _req("a", n=16), clock())    # different graph_key
    first = b.take_ready()
    second = b.take_ready()
    assert len(first) == 1 and len(second) == 1
    assert first[0].graph_key != second[0].graph_key


def test_batcher_sheds_expired():
    clock = FakeClock()
    b = ShapeBucketBatcher(max_batch=8, max_wait_us=0, clock=clock)
    _offer(b, _req("a", deadline_s=1.0), clock())
    clock.advance(2.0)
    assert len(b.shed_expired()) == 1
    assert b.staged() == 0 and b.take_ready() is None


# -- loop policy (single-threaded, stub device) ------------------------------

def test_loop_queue_full_response_at_admission():
    loop, stub, _ = _policy_loop(queue_cap=2)
    r1, r2, r3 = _req("a", seed=1), _req("a", seed=2), _req("b", seed=3)
    assert loop.submit(r1) and loop.submit(r2)
    assert not loop.submit(r3)
    resp = r3.result(timeout=0)        # completed synchronously
    assert resp.status == "queue_full"
    assert stub.dispatched == []       # never touched the device


def test_loop_expired_request_never_consumes_device_slot():
    clock = FakeClock()
    loop, stub, clock = _policy_loop(clock=clock, max_wait_us=0)
    dead = _req("a", deadline_s=5.0)
    live = _req("a", seed=9, deadline_s=100.0)
    loop.submit(dead)
    loop.submit(live)
    clock.advance(10.0)                # dead expires while queued
    _drain(loop)
    assert dead.result(timeout=0).status == "shed"
    assert live.result(timeout=0).status == "ok"
    # the shed request got no device slot: only the live one dispatched
    assert sum(stub.dispatched) == 1


def test_loop_dead_on_arrival_is_shed_at_admission():
    clock = FakeClock(100.0)
    loop, stub, _ = _policy_loop(clock=clock)
    doa = _req("a", deadline_s=50.0)   # already past deadline
    assert not loop.submit(doa)
    assert doa.result(timeout=0).status == "shed"
    assert len(loop.queue) == 0 and stub.dispatched == []


def test_loop_ok_response_carries_batch_accounting():
    loop, stub, _ = _policy_loop(max_wait_us=0)
    reqs = [_req("a", seed=i) for i in range(3)]
    for r in reqs:
        loop.submit(r)
    _drain(loop)
    resps = [r.result(timeout=0) for r in reqs]
    assert all(r.status == "ok" for r in resps)
    assert {r.batch_size for r in resps} == {3}
    assert loop.device_batches == 1


def test_loop_fetch_failure_degrades_with_last_known():
    loop, stub, _ = _policy_loop(max_wait_us=0)
    first = _req("a", seed=1)
    loop.submit(first)
    _drain(loop)
    assert first.result(timeout=0).status == "ok"   # seeds last-known

    stub.fail_next = ["fetch"]
    second = _req("a", seed=2)                      # same graph shape/edges
    loop.submit(second)
    _drain(loop)
    resp = second.result(timeout=0)
    assert resp.status == "degraded"
    assert resp.ranked == first.result(timeout=0).ranked  # the stale copy


def test_loop_error_when_no_last_known():
    loop, stub, _ = _policy_loop(max_wait_us=0)
    stub.fail_next = ["dispatch"]
    r = _req("a")
    loop.submit(r)
    _drain(loop)
    assert r.result(timeout=0).status == "error"


def test_loop_open_breaker_answers_without_device():
    loop, stub, clock = _policy_loop(max_wait_us=0)
    # three consecutive failures open the breaker
    for i in range(3):
        stub.fail_next = ["dispatch"]
        r = _req("a", seed=i)
        loop.submit(r)
        _drain(loop)
        assert r.result(timeout=0).status == "error"
    assert loop.breaker.state == "open"
    dispatched_before = len(stub.dispatched)
    r = _req("a", seed=99)
    loop.submit(r)
    _drain(loop)
    assert r.result(timeout=0).status == "error"    # circuit_open, no stale
    assert "circuit_open" in r.result(timeout=0).detail
    assert len(stub.dispatched) == dispatched_before  # device untouched


def test_loop_shutdown_resolves_everything():
    loop, stub, _ = _policy_loop(max_wait_us=10_000_000, max_batch=64)
    reqs = [_req("a", seed=i) for i in range(4)]
    for r in reqs:
        loop.submit(r)
    loop.start()
    loop.stop()
    assert all(r.done() for r in reqs)  # nobody left parked forever


# -- batching parity (real engine) -------------------------------------------

@pytest.fixture(scope="module")
def engine():
    return GraphEngine()


def test_dispatcher_parity_across_widths(engine):
    """A lane of any coalesced width is bit-identical to the solo
    analysis: same vmapped executable, lanes do not interact."""
    case = synthetic_cascade_arrays(60, n_roots=1, seed=3)
    rng = np.random.default_rng(0)
    disp = BatchDispatcher(engine)
    for width in (1, 2, 3, 5):
        reqs = [
            ServeRequest(
                tenant=f"t{i % 2}",
                features=np.clip(
                    case.features + rng.uniform(
                        0, 0.05, case.features.shape
                    ).astype(np.float32), 0, 1),
                dep_src=case.dep_src, dep_dst=case.dep_dst,
                names=case.names, k=3,
            )
            for i in range(width)
        ]
        results = disp.fetch(disp.dispatch(reqs))
        assert len(results) == width
        for req, res in zip(reqs, results):
            solo = engine.analyze_arrays(
                req.features, case.dep_src, case.dep_dst, case.names, k=3,
            )
            assert res.ranked == solo.ranked
            assert np.array_equal(res.score, solo.score)


def test_selftest_contract(engine):
    """The tier-1 smoke behind ``rca serve --selftest``: 32 mixed-tenant
    requests over three shape buckets, concurrent submitters — all
    answered or shed within deadline, coalesced-vs-solo bit parity."""
    out = serve_selftest(n_requests=32, seed=0, engine=engine)
    assert out["ok"], out
    assert out["all_resolved"] and out["parity_ok"]
    assert out["by_status"].get("shed", 0) >= out["expected_shed_min"]
    # batching actually happened: far fewer device batches than requests
    assert out["device_batches"] < out["requests"] // 2
    assert out["metrics"]["batch_occupancy_max"] > 1


def test_selftest_parity_under_chaos(engine):
    """Seeded dispatch/fetch faults: every request still resolves, and
    every ok ranking is still bit-identical to solo (degraded responses
    are stale by contract and excluded from parity)."""
    out = serve_selftest(n_requests=24, seed=3, engine=engine, chaos=True)
    assert out["all_resolved"], out
    assert out["parity_ok"], out
    assert out["ok"], out


# -- coordinator integration -------------------------------------------------

def test_coordinator_routes_correlation_through_serve(engine, five_svc_client):
    from rca_tpu.coordinator import RCACoordinator

    with ServeClient(engine=engine) as client:
        coord = RCACoordinator(
            five_svc_client, serve=client, tenant="coord-test",
        )
        record = coord.run_analysis("comprehensive", "test-microservices")
        assert record["status"] == "completed", record.get("error")
        correlated = record["results"]["correlated"]
        # the fusion result came through the serving queue
        assert correlated["engine"] == "serve+single"
        assert correlated["root_causes"]
        assert client.loop.device_batches >= 1


def test_coordinator_rejects_engine_and_serve(five_svc_client):
    from rca_tpu.coordinator import RCACoordinator

    with ServeClient(dispatcher=StubDispatcher()) as client:
        with pytest.raises(ValueError, match="not both"):
            RCACoordinator(
                five_svc_client, serve=client, engine=object(),
            )


# -- metrics -----------------------------------------------------------------

def test_metrics_summary_shape():
    loop, stub, _ = _policy_loop(max_wait_us=0)
    for i in range(3):
        loop.submit(_req("a", seed=i))
    _drain(loop)
    m = loop.metrics.summary()
    assert m["tenants"]["a"]["answered"] == 3
    assert m["tenants"]["a"]["queue_ms_p50"] is not None
    assert m["batches"] == 1
    assert m["batch_occupancy_mean"] == 3.0
    assert m["dispatched_requests"] == 3


def test_phase_stats_quantile():
    from rca_tpu.obslog.profiling import PhaseStats

    ps = PhaseStats()
    for v in range(1, 101):
        ps.record("q", float(v))
    assert ps.quantile("q", 0.0) == 1.0
    assert ps.quantile("q", 0.5) == 51.0   # nearest-rank on 100 samples
    assert ps.quantile("q", 1.0) == 100.0
    assert ps.quantile("missing", 0.5) is None
    assert ps.count("q") == 100


# -- concurrent submission through the client --------------------------------

def test_concurrent_submitters_all_resolve(engine):
    case = synthetic_cascade_arrays(48, n_roots=1, seed=1)
    rng = np.random.default_rng(0)
    feats = [
        np.clip(case.features + rng.uniform(
            0, 0.05, case.features.shape
        ).astype(np.float32), 0, 1)
        for _ in range(16)
    ]
    with ServeClient(engine=engine) as client:
        reqs = [None] * 16

        def submit(w):
            for i in range(w, 16, 4):
                reqs[i] = client.submit(
                    feats[i], case.dep_src, case.dep_dst,
                    tenant=f"t{w}", k=3,
                )

        threads = [threading.Thread(target=submit, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        resps = [r.result(120.0) for r in reqs]
    assert all(r.status == "ok" for r in resps)
    # one graph key: the sweep coalesced instead of 16 solo dispatches
    assert client.loop.device_batches < 16
