"""Flight recorder (rca_tpu/replay, REPLAY.md): record -> replay bit
parity at every pipeline depth and engine kind, clean rejection of
truncated/corrupt/foreign logs, seek/bisect divergence tooling, minting,
the serve recording path, and the store's recording_ref plumbing."""

from __future__ import annotations

import json
import os
import shutil

import numpy as np
import pytest

from rca_tpu.cluster.generator import (
    synthetic_cascade_arrays,
    synthetic_cascade_world,
)
from rca_tpu.replay import (
    ReplayFormatError,
    bisect_divergence,
    digest_obj,
    load_recording,
    mint_recording,
    read_frames,
    replay_serve,
    replay_stream,
)
from rca_tpu.replay.format import MAGIC, RecordingWriter, _MAGIC_PREFIX
from rca_tpu.resilience.chaos import ChaosConfig, run_chaos_soak

SOAK_TICKS = 60
SOAK_SVC = 30


def _soak(record_path, ticks=SOAK_TICKS, seed=7, pipeline_depth=None,
          replay_check=False):
    return run_chaos_soak(
        lambda: synthetic_cascade_world(SOAK_SVC, n_roots=1, seed=0),
        "synthetic", seed=seed, ticks=ticks,
        config=ChaosConfig(seed=seed),
        record_path=str(record_path), pipeline_depth=pipeline_depth,
        replay_check=replay_check,
    )


@pytest.fixture(scope="module")
def recorded_soak(tmp_path_factory):
    """One 60-tick chaos soak, flight-recorded — shared by every test
    that only READS the recording."""
    path = str(tmp_path_factory.mktemp("replay") / "soak")
    summary = _soak(path)
    return path, summary


# ---------------------------------------------------------------------------
# round-trip parity (the tentpole property)
# ---------------------------------------------------------------------------

def test_chaos_soak_records_and_replays_bit_identical(recorded_soak):
    """60-tick chaos run recorded then replayed: every delivered ranking
    is bit-identical, every recorded cluster call was consumed, and the
    recording closed cleanly."""
    path, summary = recorded_soak
    assert summary["uncaught_exceptions"] == 0
    assert summary["replay"]["ticks_recorded"] == SOAK_TICKS
    report = replay_stream(path)
    assert report["parity_ok"], report
    assert report["ticks_replayed"] == SOAK_TICKS
    assert report["first_divergent_tick"] is None
    assert report["unconsumed_calls"] == 0
    assert report["clean_close"]
    assert report["read_status"]["clean"]


def test_depth2_record_replay_parity(tmp_path):
    """60-tick chaos run recorded at pipeline depth 2, replayed at depth
    2: the delivered (lagged) sequences match tick for tick."""
    path = str(tmp_path / "d2")
    summary = _soak(path, seed=3, pipeline_depth=2, replay_check=True)
    assert summary["replay"]["parity_ok"], summary["replay"]
    assert summary["replay"]["ticks_replayed"] == SOAK_TICKS
    rec = load_recording(path)
    assert rec.session_info["pipeline_depth"] == 2


def test_sharded_recorded_soak_replays(tmp_path):
    """60-tick chaos run recorded WITH the sharded engine replays bit
    identically — and `auto` replay picks the recorded (sharded) kind."""
    from rca_tpu.engine.sharded_runner import ShardedGraphEngine

    path = str(tmp_path / "sh")
    summary = run_chaos_soak(
        lambda: synthetic_cascade_world(SOAK_SVC, n_roots=1, seed=0),
        "synthetic", seed=4, ticks=SOAK_TICKS, config=ChaosConfig(seed=4),
        engine_factory=lambda: ShardedGraphEngine(spec="sp=4"),
        record_path=path, replay_check=True,
    )
    assert summary["uncaught_exceptions"] == 0
    assert summary["replay"]["parity_ok"], summary["replay"]
    rec = load_recording(path)
    assert rec.session_info["engine"] == "ShardedGraphEngine"
    report = replay_stream(path, ticks=8)
    assert report["engine_replayed"] == "ShardedGraphEngine"
    assert report["parity_ok"], report


def test_sharded_replay_of_recording(recorded_soak):
    """A recording replays bit-identically on the SHARDED engine — the
    capture path asks the cluster the same questions regardless of
    engine, and the engines are parity-locked."""
    from rca_tpu.engine.sharded_runner import ShardedGraphEngine

    path, _ = recorded_soak
    report = replay_stream(path, engine=ShardedGraphEngine(spec="sp=4"),
                           ticks=20)
    assert report["parity_ok"], report
    assert report["engine_replayed"] == "ShardedGraphEngine"


def test_cross_depth_replay_compares_serial_sequences(tmp_path):
    """Replaying a depth-1 recording at depth 2 shifts delivery by one
    tick; the report compares the lag-stripped serial sequences.  Uses a
    FAULT-FREE recording: degradation flushes re-fill the pipeline and
    legitimately shift chaotic logs' delivery alignment."""
    path = str(tmp_path / "clean")
    run_chaos_soak(
        lambda: synthetic_cascade_world(SOAK_SVC, n_roots=1, seed=0),
        "synthetic", seed=1, ticks=20,
        config=ChaosConfig(seed=1, enabled=False),
        record_path=path, replay_check=False,
    )
    report = replay_stream(path, pipeline_depth=2)
    assert report["pipeline_depth_recorded"] == 1
    assert report["pipeline_depth_replayed"] == 2
    assert report["parity_ok"], report
    assert report["serial_ticks_compared"] >= 18


def test_replay_reports_env_fingerprints(recorded_soak):
    path, _ = recorded_soak
    rec = load_recording(path)
    env = rec.header["env"]
    assert env["jax"] and env["numpy"] and env["jax_backend"]
    report = replay_stream(path, ticks=3)
    assert report["env_recorded"]["jax"] == report["env_replay"]["jax"]


# ---------------------------------------------------------------------------
# broken-log handling (truncated tail, corrupt CRC, foreign schema)
# ---------------------------------------------------------------------------

def _copy_recording(src, dst):
    shutil.copytree(src, dst)
    return sorted(
        os.path.join(dst, n) for n in os.listdir(dst)
        if n.endswith(".rcr")
    )


def test_truncated_tail_stops_cleanly(recorded_soak, tmp_path):
    """A crash mid-append leaves a partial frame: the reader stops at the
    last good frame and replay covers exactly the complete ticks."""
    src, _ = recorded_soak
    dst = str(tmp_path / "truncated")
    chunks = _copy_recording(src, dst)
    last = chunks[-1]
    size = os.path.getsize(last)
    with open(last, "r+b") as f:
        f.truncate(size - 7)  # mid-frame: kills the end frame at least
    frames, status = read_frames(dst)
    assert status.truncated and not status.corrupt
    assert frames  # the good prefix survives
    report = replay_stream(dst)
    assert not report["clean_close"]
    assert report["read_status"]["truncated"]
    assert 0 < report["ticks_replayed"] <= SOAK_TICKS
    assert report["parity_ok"], report  # complete ticks still bit-match


def test_corrupt_crc_stops_cleanly(recorded_soak, tmp_path):
    src, _ = recorded_soak
    dst = str(tmp_path / "corrupt")
    chunks = _copy_recording(src, dst)
    target = chunks[0]
    # flip one payload byte well past the magic + first frames
    with open(target, "r+b") as f:
        f.seek(os.path.getsize(target) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    frames, status = read_frames(dst)
    assert status.corrupt
    assert "CRC" in status.detail or "undecodable" in status.detail
    report = replay_stream(dst)
    assert not report["clean_close"]
    assert report["ticks_replayed"] < SOAK_TICKS
    assert report["parity_ok"], report


def test_schema_version_mismatch_is_an_error(recorded_soak, tmp_path):
    src, _ = recorded_soak
    dst = str(tmp_path / "future")
    chunks = _copy_recording(src, dst)
    with open(chunks[0], "r+b") as f:
        f.seek(len(_MAGIC_PREFIX))
        f.write(bytes([99]))  # a schema version this build does not read
    with pytest.raises(ReplayFormatError, match="version 99"):
        read_frames(dst)
    with open(chunks[0], "r+b") as f:
        f.write(b"NOTAREC!")
    with pytest.raises(ReplayFormatError, match="not a flight recording"):
        read_frames(dst)


def test_empty_directory_is_not_a_recording(tmp_path):
    with pytest.raises(FileNotFoundError):
        read_frames(str(tmp_path))


# ---------------------------------------------------------------------------
# seek / bisect
# ---------------------------------------------------------------------------

def test_seek_time_travel(recorded_soak):
    path, _ = recorded_soak
    report = replay_stream(path, seek=11)
    detail = report["seek"]
    assert detail["tick"] == 11
    assert detail["replayed_ranked"] == detail["recorded_ranked"]
    assert detail["replayed_features_digest"]
    # seek stops the replay at the target tick
    assert report["ticks_replayed"] == 11


def _perturb(src, out, from_tick):
    """Rewrite a recording with every tick >= from_tick's recorded
    ranking bumped — a synthetic persistent divergence."""
    frames, status = read_frames(src)
    assert status.clean
    w = RecordingWriter(out, single_file=True)
    for fr in frames:
        if fr.get("kind") == "tick" and fr["tick"] >= from_tick:
            fr = dict(fr)
            fr["ranked"] = [
                {**r, "score": r["score"] + 1.0} for r in fr["ranked"]
            ]
            fr["ranked_digest"] = digest_obj(fr["ranked"])
        w.append(fr)
    w.close()


def test_bisect_names_the_exact_first_divergent_tick(tmp_path):
    path = str(tmp_path / "short")
    _soak(path, ticks=16, seed=5)
    perturbed = str(tmp_path / "perturbed.rcz")
    _perturb(path, perturbed, from_tick=9)
    report = bisect_divergence(perturbed)
    assert report["divergent"]
    assert report["first_divergent_tick"] == 9
    # log-bounded probing, not one replay per tick
    assert report["probes"] <= 6
    dump = json.load(open(report["dump"]))
    assert dump["tick"] == 9
    assert dump["recorded_ranked"] != dump["replayed_ranked"]
    assert dump["replayed_features_digest"]
    # the soaked graph is small, so full recorded rows rode along and the
    # dump carries an explicit tensor diff
    assert dump["recorded_features"] is not None
    assert dump["feature_diff"]["max_abs"] == 0.0  # rankings perturbed,
    # features untouched: the diff localizes divergence to the engine side

    clean = bisect_divergence(path)
    assert not clean["divergent"]
    assert clean["first_divergent_tick"] is None


def test_replay_exit_contract_on_divergence(tmp_path):
    """`rca replay` exits 1 on divergence and names the first tick."""
    from rca_tpu.cli import main

    path = str(tmp_path / "short")
    _soak(path, ticks=12, seed=9)
    perturbed = str(tmp_path / "p.rcz")
    _perturb(path, perturbed, from_tick=6)
    assert main(["replay", path, "--compact"]) == 0
    assert main(["replay", perturbed, "--compact"]) == 1
    assert main(["replay", perturbed, "--bisect", "--compact"]) == 1


# ---------------------------------------------------------------------------
# minting (corpus fixtures)
# ---------------------------------------------------------------------------

def test_mint_round_trip(recorded_soak, tmp_path):
    path, _ = recorded_soak
    out = str(tmp_path / "fixture.rcz")
    stats = mint_recording(path, out)
    assert stats["ticks"] == SOAK_TICKS
    assert os.path.getsize(out) == stats["bytes_out"]
    report = replay_stream(out)
    assert report["parity_ok"], report
    assert report["ticks_replayed"] == SOAK_TICKS


def test_mint_refuses_partial_evidence(recorded_soak, tmp_path):
    src, _ = recorded_soak
    dst = str(tmp_path / "broken")
    chunks = _copy_recording(src, dst)
    with open(chunks[-1], "r+b") as f:
        f.truncate(os.path.getsize(chunks[-1]) - 3)
    with pytest.raises(ValueError, match="refusing to mint"):
        mint_recording(dst, str(tmp_path / "nope.rcz"))


def test_chunk_rotation_and_fsync_boundaries(tmp_path):
    """A tiny chunk budget forces rotation; the reader stitches chunks
    back into one frame stream."""
    from rca_tpu.replay import Recorder

    path = str(tmp_path / "chunks")
    rec = Recorder(path, chunk_bytes=4096)
    rec.begin_session({"namespace": "x"})
    for t in range(1, 40):
        rec.begin_tick(t)
        rec.record_call("get_pods", "[\"x\"]", ok=True,
                        result=[{"metadata": {"name": f"p{t}"}}] * 20)
        rec.end_tick({"ranked": [{"component": "p", "score": 1.0}]},
                     features=np.zeros((4, 3), np.float32))
    rec.close()
    n_chunks = len([n for n in os.listdir(path) if n.endswith(".rcr")])
    assert n_chunks > 1
    frames, status = read_frames(path)
    assert status.clean and status.chunks == n_chunks
    loaded = load_recording(path)
    assert len(loaded.ticks) == 39
    assert loaded.clean_close


# ---------------------------------------------------------------------------
# serve recordings
# ---------------------------------------------------------------------------

def _serve_some(tmp_path, store=None, investigation_id=None, n=6):
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.config import ServeConfig
    from rca_tpu.replay import Recorder
    from rca_tpu.serve import ServeClient, ServeLoop

    case = synthetic_cascade_arrays(40, n_roots=1, seed=0)
    rng = np.random.default_rng(0)
    path = str(tmp_path / "serve-rec")
    recorder = Recorder(path, mode="serve")
    loop = ServeLoop(engine=GraphEngine(),
                     config=ServeConfig(max_batch=4, max_wait_us=500),
                     recorder=recorder, store=store)
    with loop:
        client = ServeClient(loop)
        reqs = [
            client.submit(
                np.clip(case.features + rng.uniform(
                    0, 0.05, case.features.shape).astype(np.float32), 0, 1),
                case.dep_src, case.dep_dst, names=case.names,
                tenant=f"t{i % 2}", k=5,
                investigation_id=investigation_id,
            )
            for i in range(n)
        ]
        responses = [r.result(timeout=120.0) for r in reqs]
    recorder.close()
    return path, responses


def test_serve_record_then_replay_bit_identical(tmp_path):
    """Requests served from arbitrary coalesced batches replay SOLO with
    bit-identical rankings (the serving parity contract made durable)."""
    path, responses = _serve_some(tmp_path)
    assert all(r.ok for r in responses)
    report = replay_serve(path)
    assert report["requests_recorded"] == len(responses)
    assert report["parity_ok"], report
    assert report["clean_close"]


def test_replay_dispatches_on_mode(tmp_path):
    from rca_tpu.replay import replay

    path, _ = _serve_some(tmp_path, n=2)
    report = replay(path)
    assert report["mode"] == "serve" and report["parity_ok"]


def test_serve_replay_divergence_names_request(tmp_path):
    path, _ = _serve_some(tmp_path, n=3)
    frames, _ = read_frames(path)
    out = str(tmp_path / "p.rcz")
    w = RecordingWriter(out, single_file=True)
    for fr in frames:
        if fr.get("kind") == "serve" and fr["index"] == 1:
            fr = dict(fr)
            fr["ranked_digest"] = "0" * 16
        w.append(fr)
    w.close()
    report = replay_serve(out)
    assert not report["parity_ok"]
    assert report["first_divergent_index"] == 1


# ---------------------------------------------------------------------------
# store integration (recording_ref)
# ---------------------------------------------------------------------------

def test_store_recording_ref_round_trip(tmp_path):
    from rca_tpu.store import InvestigationStore

    store = InvestigationStore(root=str(tmp_path / "logs"))
    inv = store.create_investigation("incident", recording_ref="/rec/a")
    assert store.get_recording_ref(inv["id"]) == "/rec/a"
    store.set_recording_ref(inv["id"], "/rec/b")
    assert store.get_investigation(inv["id"])["recording_ref"] == "/rec/b"
    rows = store.list_investigations()
    assert rows and rows[0]["replayable"] is True


def test_served_investigation_is_replayable_by_id(tmp_path):
    """The full satellite path: a served analysis with an investigation
    id stamps recording_ref, and `rca replay --investigation <id>`
    re-drives it from the id alone."""
    from rca_tpu.cli import main
    from rca_tpu.store import InvestigationStore

    log_dir = str(tmp_path / "logs")
    store = InvestigationStore(root=log_dir)
    inv = store.create_investigation("served incident")
    path, responses = _serve_some(tmp_path, store=store,
                                  investigation_id=inv["id"], n=3)
    assert all(r.ok for r in responses)
    assert store.get_recording_ref(inv["id"]) == path
    assert main(["replay", "--investigation", inv["id"],
                 "--log-dir", log_dir, "--compact"]) == 0
    # unknown ref -> error, exit 1
    other = store.create_investigation("no recording")
    assert main(["replay", "--investigation", other["id"],
                 "--log-dir", log_dir, "--compact"]) == 1


# ---------------------------------------------------------------------------
# recorder mechanics
# ---------------------------------------------------------------------------

def test_recording_proxy_preserves_optional_surfaces(recorded_soak):
    """hasattr parity: a chaos recording replays WITH drain_injected
    (the session's health path used it), and the replay source refuses
    methods the recording never saw."""
    path, _ = recorded_soak
    from rca_tpu.replay.source import ReplaySource

    rec = load_recording(path)
    src = ReplaySource(rec.calls)
    assert hasattr(src, "drain_injected")
    assert hasattr(src, "watch_changes")
    assert not hasattr(src, "watch_close")  # mock never had it
    with pytest.raises(AttributeError):
        src.get_nonexistent_surface


def test_replay_mismatch_is_loud(recorded_soak):
    from rca_tpu.replay.source import ReplayMismatch, ReplaySource

    path, _ = recorded_soak
    rec = load_recording(path)
    src = ReplaySource(rec.calls)
    src.advance(1)
    with pytest.raises(ReplayMismatch, match="tick 1"):
        src.get_pods("a-namespace-never-recorded")


def test_recorded_faults_replay_as_faults(recorded_soak):
    """Chaos-injected exceptions are part of the tape: at least one
    recorded call failed, and the replayed soak still hit degraded
    paths without diverging (covered by the parity test) — here we
    check the error frames round-trip with their types."""
    path, summary = recorded_soak
    rec = load_recording(path)
    errors = [c for c in rec.calls if not c["ok"]]
    if summary["faults_injected"].get("api_timeout", 0) == 0:
        pytest.skip("seed injected no api_timeout this run")
    assert any(c["error_type"] == "InjectedTimeout" for c in errors)


def test_magic_layout_is_stable():
    """The on-disk magic is a compatibility contract; changing it must
    be a deliberate schema bump, not an accident."""
    assert MAGIC == b"RCAREC\x01\n"
