"""Corpus gate (REPLAY.md): every committed flight-recording fixture
under tests/corpus/ replays bit-identically through the real engine.

A fixture here is a minted chaos/stream/serve recording — a PERMANENT
regression test: any change that shifts one ranked bit on any recorded
tick fails this gate with the exact tick (or request) named.  Fixtures
are platform evidence: they were recorded on the CPU backend this suite
runs on (the header's env fingerprint says so), which is what makes the
bitwise assertion legitimate.
"""

from __future__ import annotations

import glob
import os

import pytest

from rca_tpu.replay import load_recording, replay

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus")
FIXTURES = sorted(glob.glob(os.path.join(CORPUS_DIR, "*.rcz")))


def _label(path):
    return os.path.basename(path)


def test_corpus_is_not_empty():
    """The corpus gate must be guarding something — PR 5 commits the
    first minted chaos run."""
    assert FIXTURES, f"no *.rcz fixtures under {CORPUS_DIR}"


@pytest.mark.parametrize("path", FIXTURES, ids=_label)
def test_fixture_is_complete_evidence(path):
    """Minting refuses partial captures, and committed fixtures must
    stay that way: clean frames, clean close, matching backend."""
    rec = load_recording(path)
    assert rec.status.clean, rec.status.to_dict()
    assert rec.clean_close
    assert rec.header["env"]["jax_backend"] == "cpu", (
        "corpus fixtures must be recorded on the backend the suite "
        "replays on — bitwise parity is a per-platform claim"
    )


@pytest.mark.parametrize("path", FIXTURES, ids=_label)
def test_fixture_replays_bit_identical(path):
    report = replay(path)
    assert report["parity_ok"], {
        k: report.get(k)
        for k in ("first_divergent_tick", "first_divergent_index",
                  "mismatched_ticks", "unconsumed_calls")
    }
    if report["mode"] == "stream":
        assert report["ticks_replayed"] == report["ticks_recorded"]
        assert report["unconsumed_calls"] == 0
