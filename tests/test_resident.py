"""Device-resident session gates (ISSUE 6).

The resident analyze path's whole license to exist is bit-parity: a
request served from the pinned buffer + delta scatter must be
indistinguishable — scores, rankings, sanitized-row counts — from one
staged fresh.  These tests are that license:

- a donation-parity PROPERTY test drives a resident session through
  random update / delete(zero-reset) / NaN-poison sequences and asserts
  bit-identity against full staging at every step;
- a replay-parity leg proves the minted corpus fixture replays tick-exact
  with resident sessions enabled at pipeline depth 1 and 2 (the live
  path's engines are constructed with the env default, so the gate
  covers the integration, not just the unit);
- the serving dispatcher's delta-staged batches hold the coalesced-vs-
  solo contract, including NaN lanes and base drift;
- the supporting machinery (LRU cache, lazy EngineResult diagnostics,
  env knob validation, upload accounting) behaves as documented.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.engine.runner import GraphEngine
from rca_tpu.engine.resident import ResidentCache, graph_digest

CORPUS = os.path.join(
    os.path.dirname(__file__), "corpus", "chaos-20svc-seed11.rcz"
)


def _case(n=96, seed=0):
    return synthetic_cascade_arrays(n, n_roots=1, seed=seed)


def _assert_bitwise(a, b, ctx=""):
    assert a.ranked == b.ranked, (ctx, a.ranked, b.ranked)
    assert np.array_equal(a.score, b.score), ctx
    assert np.array_equal(a.anomaly, b.anomaly), ctx
    assert np.array_equal(a.upstream, b.upstream), ctx
    assert np.array_equal(a.impact, b.impact), ctx
    assert a.sanitized_rows == b.sanitized_rows, (
        ctx, a.sanitized_rows, b.sanitized_rows
    )


# -- donation-parity property test -------------------------------------------

def test_resident_delta_parity_property():
    """Resident delta path bit-identical to fresh full staging over
    random update/delete sequences, NaN rows included (the satellite's
    core gate)."""
    case = _case()
    n, C = case.features.shape
    resident = GraphEngine(resident=True)
    fresh = GraphEngine(resident=False)
    rng = np.random.default_rng(11)
    feats = case.features.copy()
    for step in range(12):
        kind = step % 4
        if kind == 0:      # sparse update
            rows = rng.integers(0, n, rng.integers(1, 9))
            feats[rows] = np.clip(
                feats[rows] + rng.uniform(-0.3, 0.3, (len(rows), C)),
                0, 1,
            ).astype(np.float32)
        elif kind == 1:    # delete: services going silent (zero reset)
            rows = rng.integers(0, n, 3)
            feats[rows] = 0.0
        elif kind == 2:    # poisoned telemetry: NaN/Inf rows
            feats[int(rng.integers(0, n))] = np.nan
            feats[int(rng.integers(0, n)), 0] = np.inf
        else:              # heal the poison + dense churn (wide delta)
            feats = np.nan_to_num(feats, posinf=0.0)
            feats = np.clip(
                feats + rng.uniform(-0.02, 0.02, feats.shape), 0, 1
            ).astype(np.float32)
        a = resident.analyze_arrays(
            feats, case.dep_src, case.dep_dst, case.names, k=5
        )
        b = fresh.analyze_arrays(
            feats, case.dep_src, case.dep_dst, case.names, k=5
        )
        _assert_bitwise(a, b, ctx=f"step {step} kind {kind}")
    stats = resident._resident_cache.stats()
    assert stats["delta_requests"] >= 6, stats
    assert stats["sessions"] == 1


def test_resident_identical_request_uploads_nothing():
    case = _case(48, seed=3)
    eng = GraphEngine(resident=True)
    eng.analyze_case(case, k=3)
    sess = next(iter(eng._resident_cache._sessions.values()))
    assert sess.last_upload_rows == sess._n_pad  # first staging is bulk
    eng.analyze_case(case, k=3)
    assert sess.last_upload_rows == 0            # repeat: zero upload
    assert sess.delta_requests == 1


def test_resident_upload_is_o_changed_rows():
    case = _case(200, seed=5)
    eng = GraphEngine(resident=True)
    eng.analyze_case(case, k=5)
    f2 = case.features.copy()
    f2[17] += 0.25
    f2 = np.clip(f2, 0, 1)
    eng.analyze_arrays(f2, case.dep_src, case.dep_dst, case.names, k=5)
    sess = next(iter(eng._resident_cache._sessions.values()))
    assert sess.last_upload_rows == 1            # one dirty row, pow2-padded
    assert sess.last_upload_rows < sess._n_pad


def test_resident_cache_lru_and_counters():
    eng = GraphEngine(resident=True)
    eng._resident_cache._cap = 2
    cases = [_case(40 + 8 * i, seed=i) for i in range(3)]
    for c in cases:
        eng.analyze_case(c, k=3)
    stats = eng._resident_cache.stats()
    assert stats == {**stats, "misses": 3, "evictions": 1, "sessions": 2}
    eng.analyze_case(cases[-1], k=3)             # still resident
    assert eng._resident_cache.hits == 1


def test_graph_digest_distinguishes_edges():
    c = _case(32, seed=1)
    d1 = graph_digest(32, c.features.shape[1], c.dep_src, c.dep_dst)
    d2 = graph_digest(32, c.features.shape[1], c.dep_dst, c.dep_src)
    assert d1 != d2


def test_engine_result_diagnostics_are_lazy():
    case = _case(48, seed=2)
    res = GraphEngine(resident=True).analyze_case(case, k=3)
    assert res._stacked is None and res._stacked_dev is not None
    score = res.score                            # deferred bulk fetch
    assert score.shape == (48,)
    assert res._stacked is not None and res._stacked_dev is None
    # ranked channels were rendered from the top-k gather, not the stack
    top = res.ranked[0]
    i = res.service_names.index(top["component"])
    assert top["anomaly"] == pytest.approx(float(res.anomaly[i]))
    assert top["score"] == pytest.approx(float(res.score[i]))


def test_resident_env_knobs_validated(monkeypatch):
    from rca_tpu.config import resident_cache_cap, resident_enabled

    monkeypatch.setenv("RCA_RESIDENT", "0")
    assert resident_enabled() is False
    monkeypatch.setenv("RCA_RESIDENT", "banana")
    with pytest.raises(ValueError):
        resident_enabled()
    monkeypatch.setenv("RCA_RESIDENT_CACHE", "0")
    with pytest.raises(ValueError):
        resident_cache_cap()
    monkeypatch.setenv("RCA_RESIDENT_CACHE", "16")
    assert resident_cache_cap() == 16
    monkeypatch.setenv("RCA_RESIDENT", "")
    assert resident_enabled() is True            # unset = on (default)


def test_rca_resident_off_disables_cache(monkeypatch):
    monkeypatch.setenv("RCA_RESIDENT", "0")
    assert GraphEngine()._resident_cache is None
    monkeypatch.setenv("RCA_RESIDENT", "1")
    assert GraphEngine()._resident_cache is not None


# -- serving dispatcher delta staging ----------------------------------------

def test_dispatcher_delta_batches_hold_solo_parity():
    from rca_tpu.serve import BatchDispatcher, ServeRequest
    from rca_tpu.serve.metrics import ServeMetrics

    case = _case(80, seed=7)
    engine = GraphEngine(resident=False)
    metrics = ServeMetrics()
    disp = BatchDispatcher(engine, metrics=metrics)
    rng = np.random.default_rng(0)

    def req(tag, poison=False):
        f = case.features.copy()
        rows = rng.integers(0, 80, 4)
        f[rows] = np.clip(
            f[rows] + rng.uniform(0, 0.2, (4, f.shape[1])), 0, 1
        ).astype(np.float32)
        if poison:
            f[int(rng.integers(0, 80))] = np.nan
        return ServeRequest(
            tenant=tag, features=f, dep_src=case.dep_src,
            dep_dst=case.dep_dst, names=case.names, k=3,
        )

    disp.fetch(disp.dispatch([req("warm")]))     # establishes the base
    batch = [req("a"), req("b", poison=True), req("a")]
    results = disp.fetch(disp.dispatch(batch))
    summary = metrics.summary()
    assert summary["tenants"]["a"]["resident_delta_requests"] == 2
    assert summary["tenants"]["a"]["resident_rows_saved"] > 0
    assert summary["graph_cache"]["hit"] >= 1
    for r, res in zip(batch, results):
        solo = engine.analyze_arrays(
            r.features, r.dep_src, r.dep_dst, r.names, k=3
        )
        assert solo.ranked == res.ranked
        assert np.array_equal(solo.score, res.score)


def test_dispatcher_falls_back_when_batch_drifts():
    from rca_tpu.serve import BatchDispatcher, ServeRequest

    case = _case(64, seed=9)
    disp = BatchDispatcher(GraphEngine(resident=False))
    base_req = ServeRequest(
        tenant="t", features=case.features, dep_src=case.dep_src,
        dep_dst=case.dep_dst, names=case.names, k=3,
    )
    disp.fetch(disp.dispatch([base_req]))
    gs = next(iter(disp._graphs.values()))
    drifted = np.clip(case.features + 0.5, 0, 1).astype(np.float32)
    assert disp._lane_deltas(gs, [ServeRequest(
        tenant="t", features=drifted, dep_src=case.dep_src,
        dep_dst=case.dep_dst, k=3,
    )]) is None                                  # every row dirty: restage


# -- replay-parity leg (corpus fixture through resident sessions) ------------

def test_corpus_replays_tick_exact_with_resident_sessions(monkeypatch):
    """The minted chaos fixture replays bit-identically with resident
    sessions enabled (env default) at its recorded depth — the resident
    refactor may not move one ranked bit of recorded history."""
    from rca_tpu.replay import replay_stream

    monkeypatch.setenv("RCA_RESIDENT", "1")
    report = replay_stream(CORPUS)
    assert report["pipeline_depth_replayed"] == 1
    assert report["parity_ok"], report
    assert report["ticks_replayed"] == report["ticks_recorded"]


def test_depth2_record_replay_parity_with_resident_sessions(
    tmp_path, monkeypatch,
):
    """Depth-2 leg: a chaos session recorded at pipeline depth 2 with
    resident sessions enabled replays tick-exact at depth 2.  (Cross-
    depth replay of the depth-1 corpus fixture is deliberately NOT the
    gate here: degradation flushes re-fill the pipeline and legitimately
    shift a chaotic log's delivery alignment — the replayer's documented
    like-for-like contract, see tests/test_replay.py.)"""
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.resilience.chaos import ChaosConfig, run_chaos_soak

    monkeypatch.setenv("RCA_RESIDENT", "1")
    path = str(tmp_path / "d2-resident")
    summary = run_chaos_soak(
        lambda: synthetic_cascade_world(20, n_roots=1, seed=11),
        "synthetic", seed=11, ticks=30, config=ChaosConfig(seed=11),
        record_path=path, pipeline_depth=2, replay_check=True,
    )
    assert summary["uncaught_exceptions"] == 0
    assert summary["replay"]["parity_ok"], summary["replay"]
    assert summary["replay"]["ticks_replayed"] == 30


# -- sharded one-shot resident session (ISSUE 8 satellite) -------------------

def _sharded_engines(sp=4):
    from rca_tpu.engine.sharded_runner import ShardedGraphEngine

    return (
        ShardedGraphEngine(spec=f"sp={sp}", resident=True),
        ShardedGraphEngine(spec=f"sp={sp}", resident=False),
    )


def test_sharded_resident_delta_parity_property():
    """PR 6's named leftover, closed: the sharded one-shot path gets the
    same ResidentSession-backed delta treatment — and the same bit-parity
    property gate over update/delete/NaN sequences."""
    case = _case(120, seed=7)
    n, C = case.features.shape
    resident, fresh = _sharded_engines()
    rng = np.random.default_rng(13)
    feats = case.features.copy()
    for step in range(10):
        kind = step % 4
        if kind == 0:      # sparse update
            rows = rng.integers(0, n, rng.integers(1, 6))
            feats[rows] = np.clip(
                feats[rows] + rng.uniform(-0.3, 0.3, (len(rows), C)),
                0, 1,
            ).astype(np.float32)
        elif kind == 1:    # delete: services going silent
            feats[rng.integers(0, n, 2)] = 0.0
        elif kind == 2:    # poisoned telemetry
            feats[int(rng.integers(0, n))] = np.nan
        else:              # heal + dense churn (delta stops paying)
            feats = np.nan_to_num(feats)
            feats = np.clip(
                feats + rng.uniform(-0.02, 0.02, feats.shape), 0, 1
            ).astype(np.float32)
        a = resident.analyze_arrays(
            feats, case.dep_src, case.dep_dst, case.names, k=5
        )
        b = fresh.analyze_arrays(
            feats, case.dep_src, case.dep_dst, case.names, k=5
        )
        _assert_bitwise(a, b, ctx=f"sharded step {step} kind {kind}")
    stats = resident._resident_cache.stats()
    assert stats["sessions"] == 1
    assert stats["delta_requests"] >= 4, stats


def test_sharded_resident_upload_is_o_changed_rows():
    case = _case(200, seed=5)
    resident, _ = _sharded_engines()
    resident.analyze_case(case, k=5)
    sess = next(iter(resident._resident_cache._sessions.values()))
    assert sess.last_upload_rows == sess._n_pad  # first staging is bulk
    f2 = np.clip(case.features.copy(), 0, 1)
    f2[17] = np.clip(f2[17] + 0.25, 0, 1)
    resident.analyze_arrays(f2, case.dep_src, case.dep_dst, case.names, k=5)
    assert sess.last_upload_rows == 1            # one dirty row
    resident.analyze_arrays(f2, case.dep_src, case.dep_dst, case.names, k=5)
    assert sess.last_upload_rows == 0            # identical repeat
    assert sess.delta_requests == 2


def test_sharded_resident_matches_dense_rankings():
    """Cross-engine sanity: the sharded resident path ranks like the
    dense engine (allclose contract, as for the restaged sharded path)."""
    case = _case(96, seed=2)
    sharded, _ = _sharded_engines()
    dense = GraphEngine(resident=False)
    a = sharded.analyze_case(case, k=5)
    b = dense.analyze_case(case, k=5)
    assert [r["component"] for r in a.ranked] == [
        r["component"] for r in b.ranked
    ]
    np.testing.assert_allclose(a.score, b.score, atol=2e-5)
