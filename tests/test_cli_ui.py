"""CLI commands (subprocess-free, via main(argv)) and pure UI renderers."""

import json

import pytest

from rca_tpu.cli import main
from rca_tpu.ui.render import (
    finding_markdown,
    initial_suggestions,
    report_markdown,
    response_markdown,
    root_causes_markdown,
    topology_plot_data,
)


def run_cli(capsys, *argv):
    code = main(list(argv))
    out = capsys.readouterr().out
    return code, out


def test_cli_analyze_comprehensive(capsys, tmp_path):
    code, out = run_cli(
        capsys, "analyze", "--fixture", "5svc", "--compact",
        "--log-dir", str(tmp_path),
    )
    assert code == 0
    data = json.loads(out)
    assert data["status"] == "completed"
    comps = [r["component"] for r in data["root_causes"][:2]]
    assert set(comps) == {"database", "api-gateway"}


def test_cli_analyze_single_agent(capsys, tmp_path):
    code, out = run_cli(
        capsys, "analyze", "--fixture", "5svc", "--type", "logs",
        "--compact", "--log-dir", str(tmp_path),
    )
    assert code == 0
    data = json.loads(out)
    assert any("database" in f["component"] for f in data["root_causes"])


def test_cli_chat(capsys, tmp_path):
    code, out = run_cli(
        capsys, "chat", "--fixture", "5svc", "--compact",
        "--log-dir", str(tmp_path), "what is broken?",
    )
    assert code == 0
    data = json.loads(out)
    assert data["response_data"]["points"]
    assert data["suggestions"]


def test_cli_suggest(capsys, tmp_path):
    code, out = run_cli(
        capsys, "suggest", "--fixture", "5svc", "--compact",
        "--log-dir", str(tmp_path),
        json.dumps({"type": "check_logs",
                    "pod_name": "database-7c9f8b6d5e-3x5qp"}),
    )
    assert code == 0
    data = json.loads(out)
    assert data["key_findings"]


def test_cli_synthetic_fixture(capsys, tmp_path):
    code, out = run_cli(
        capsys, "analyze", "--fixture", "50svc", "--compact",
        "--log-dir", str(tmp_path),
    )
    assert code == 0
    data = json.loads(out)
    assert data["status"] == "completed"
    assert data["root_causes"]


def test_cli_investigations(capsys, tmp_path):
    from rca_tpu.store import InvestigationStore

    store = InvestigationStore(root=str(tmp_path))
    inv = store.create_investigation("t1")
    code, out = run_cli(capsys, "investigations", "--log-dir", str(tmp_path))
    assert code == 0
    assert json.loads(out)[0]["id"] == inv["id"]
    code, out = run_cli(
        capsys, "investigations", "--log-dir", str(tmp_path),
        "--id", inv["id"],
    )
    assert code == 0
    assert json.loads(out)["title"] == "t1"
    code, _ = run_cli(
        capsys, "investigations", "--log-dir", str(tmp_path), "--id", "nope",
    )
    assert code == 1


def test_cli_unknown_fixture(tmp_path):
    with pytest.raises(SystemExit):
        main(["analyze", "--fixture", "banana"])


def test_render_helpers():
    sugg = initial_suggestions("prod")
    assert len(sugg) == 5
    assert sugg[0]["action"]["type"] == "run_agent"

    f = {"component": "Pod/x", "issue": "boom", "severity": "critical",
         "recommendation": "fix it", "source": "logs"}
    md = finding_markdown(f)
    assert "Pod/x" in md and "critical" in md

    correlated = {
        "backend": "jax",
        "root_causes": [
            {"component": "database", "score": 1.5, "finding_count": 3,
             "severity": "critical"},
        ],
        "engine_latency_ms": 12.5,
    }
    md = root_causes_markdown(correlated)
    assert "database" in md and "12.5 ms" in md

    from rca_tpu.ui.render import diagnostic_timeline_markdown

    assert "No steps" in diagnostic_timeline_markdown([])
    tl = diagnostic_timeline_markdown([
        {"step": {"description": "Check logs of db-0"},
         "verdict": {"verdict": "supported", "confidence": 0.8,
                     "reasoning": "exit 1 in previous logs"}},
    ])
    assert "Check logs of db-0" in tl and "supported" in tl and "80%" in tl

    md = response_markdown(
        {"points": ["p1"], "sections": [{"title": "T", "content": ["c1"]}]}
    )
    assert "- p1" in md and "**T**" in md

    rep = report_markdown(
        {"correlated": correlated, "summary": "all broken",
         "logs": {"findings": [f], "summary": "1 log finding"}}
    )
    assert "Root Cause Analysis Report" in rep
    assert "all broken" in rep and "Pod/x" in rep


def test_topology_plot_data_layout():
    graph = {
        "nodes": [
            {"id": "service/a", "type": "service"},
            {"id": "service/b", "type": "service"},
            {"id": "workload/w", "type": "workload"},
        ],
        "edges": [
            {"source": "service/a", "target": "workload/w",
             "relation": "selects"},
            {"source": "service/a", "target": "ghost", "relation": "routes"},
        ],
    }
    data = topology_plot_data(graph)
    assert len(data["nodes"]) == 3
    # edges to unknown nodes are dropped, coords attached
    assert len(data["edges"]) == 1
    e = data["edges"][0]
    assert {"x0", "y0", "x1", "y1"} <= set(e)
    # deterministic: same input, same layout
    assert topology_plot_data(graph) == data


def test_ui_app_importable_without_streamlit():
    import rca_tpu.ui.app  # noqa: F401


def test_analysis_chart_series_per_agent():
    """Every agent's viz payload yields renderable chart specs — the UI
    renders these with st.bar_chart/st.dataframe per agent tab (reference:
    components/visualization.py per-type renderers)."""
    from rca_tpu.ui.render import analysis_chart_series, analysis_viz_data

    logs_result = {
        "findings": [
            {"component": "Pod/x", "severity": "high",
             "evidence": {"pattern": "oom_kill", "count": 3}},
        ],
    }
    charts = analysis_chart_series(analysis_viz_data("logs", logs_result))
    titles = {c["title"] for c in charts}
    assert "Findings by severity" in titles
    assert "Log error classes" in titles
    by_title = {c["title"]: c for c in charts}
    assert by_title["Log error classes"]["data"] == {"oom_kill": 3}

    # real metrics findings always carry a 'resource' kind (agents/metrics
    # emits one finding per resource), so one component can own several
    # bars — cpu and memory must not overwrite each other
    metrics_result = {
        "findings": [
            {"component": "Pod/y", "severity": "medium",
             "evidence": {"usage_percentage": 92.0, "resource": "cpu"}},
            {"component": "Pod/y", "severity": "medium",
             "evidence": {"usage_percentage": 61.0, "resource": "memory"}},
        ],
    }
    charts = analysis_chart_series(
        analysis_viz_data("metrics", metrics_result)
    )
    util = next(c for c in charts if c["title"].startswith("Utilization"))
    assert util["data"] == {"Pod/y (cpu)": 92.0, "Pod/y (memory)": 61.0}

    res_result = {"findings": [],
                  "data": {"pod_buckets": {"crashloop": 2, "pending": 0}}}
    charts = analysis_chart_series(
        analysis_viz_data("resources", res_result)
    )
    buckets = next(c for c in charts if "buckets" in c["title"])
    assert buckets["data"] == {"crashloop": 2}  # zero buckets dropped

    topo_result = {
        "findings": [],
        "data": {"service_pod_mapping": {"svc-a": {"ready": 1, "total": 2}}},
    }
    charts = analysis_chart_series(
        analysis_viz_data("topology", topo_result)
    )
    table = next(c for c in charts if c["kind"] == "table")
    assert table["data"][0]["service"] == "svc-a"


def test_chart_series_per_type_richness():
    """Round-3 per-type chart parity (VERDICT r2 item 8): metrics carry
    the 80/90% rule-engine threshold lines, events break down by reason
    and type, traces chart latency percentiles, and every agent emits a
    severity-tagged findings table."""
    from rca_tpu.ui.render import analysis_chart_series, analysis_viz_data

    metrics_result = {
        "findings": [
            {"component": "Pod/y", "severity": "high",
             "evidence": {"usage_percentage": 95.0, "resource": "cpu"},
             "issue": "CPU utilization at 95% of its limit"},
        ],
    }
    charts = analysis_chart_series(
        analysis_viz_data("metrics", metrics_result)
    )
    util = next(c for c in charts if c["title"].startswith("Utilization"))
    assert [t["value"] for t in util["thresholds"]] == [80, 90]
    ftable = next(c for c in charts if c["kind"] == "findings_table")
    assert ftable["data"][0]["severity"] == "high"
    assert ftable["data"][0]["component"] == "Pod/y"
    assert ftable["data"][0]["icon"]  # severity color carrier

    events_result = {
        "findings": [],
        "data": {
            "reason_counts": {"BackOff": 12, "Unhealthy": 3},
            "type_counts": {"Warning": 15},
        },
    }
    charts = analysis_chart_series(
        analysis_viz_data("events", events_result)
    )
    titles = {c["title"]: c for c in charts}
    assert titles["Events by reason"]["data"] == {
        "BackOff": 12, "Unhealthy": 3,
    }
    assert titles["Events by type"]["data"] == {"Warning": 15}

    traces_result = {
        "findings": [],
        "data": {"latency": {"svc-a": {"p50": 10, "p95": 120, "p99": 300}}},
    }
    charts = analysis_chart_series(
        analysis_viz_data("traces", traces_result)
    )
    lat = next(c for c in charts if "latency" in c["title"])
    assert lat["data"] == {"svc-a": 120}


def test_agents_emit_viz_data_payloads(five_svc_client):
    """The events/traces agents attach the chart payloads the UI renders."""
    from rca_tpu.agents import AnalysisContext, make_agents
    from rca_tpu.cluster.fixtures import NS
    from rca_tpu.cluster.snapshot import ClusterSnapshot

    ctx = AnalysisContext(ClusterSnapshot.capture(five_svc_client, NS))
    agents = make_agents()
    ev = agents["events"].analyze(ctx).to_dict()
    assert ev["data"]["reason_counts"]
    assert sum(ev["data"]["type_counts"].values()) >= sum(
        1 for _ in ctx.snapshot.events
    )
    tr = agents["traces"].analyze(ctx).to_dict()
    assert "latency" in tr.get("data", {})
    assert all(isinstance(v, dict) for v in tr["data"]["latency"].values())


def test_render_chart_dispatch_without_plotly(monkeypatch):
    """The Streamlit chart dispatcher handles every spec kind with the
    plotly-free fallbacks: threshold bars degrade to caption+bar, findings
    tables carry the icon column.  The ImportError path is FORCED (a None
    sys.modules entry makes `import plotly...` raise) so the assertions
    don't flip on machines where plotly happens to be installed."""
    import sys

    monkeypatch.setitem(sys.modules, "plotly", None)
    monkeypatch.setitem(sys.modules, "plotly.graph_objects", None)

    from rca_tpu.ui.app import _render_chart

    class FakeSt:
        def __init__(self):
            self.calls = []

        def bar_chart(self, data):
            self.calls.append(("bar_chart", data))

        def dataframe(self, data, **kw):
            self.calls.append(("dataframe", data))

        def caption(self, text):
            self.calls.append(("caption", text))

    st = FakeSt()
    _render_chart(st, {
        "kind": "bar", "title": "Utilization",
        "data": {"Pod/y (cpu)": 95.0},
        "thresholds": [{"value": 80, "label": "warn (80%)"},
                       {"value": 90, "label": "critical (90%)"}],
    })
    kinds = [c[0] for c in st.calls]
    assert "bar_chart" in kinds
    # thresholds surfaced even without plotly
    assert any("warn (80%)" in str(c[1]) for c in st.calls
               if c[0] == "caption")

    st = FakeSt()
    _render_chart(st, {
        "kind": "findings_table", "title": "Findings",
        "data": [{"icon": "🔴", "severity": "critical",
                  "component": "Pod/x", "issue": "boom"}],
    })
    rows = next(c[1] for c in st.calls if c[0] == "dataframe")
    assert rows[0][""] == "🔴" and rows[0]["component"] == "Pod/x"

    st = FakeSt()
    _render_chart(st, {"kind": "table", "title": "t", "data": [{"a": 1}]})
    assert st.calls[0][0] == "dataframe"


def test_correlated_markdown_groups():
    from rca_tpu.ui.render import correlated_markdown

    correlated = {
        "root_causes": [{"component": "database"}],
        "groups": {
            "database": [
                {"severity": "critical", "source": "logs"},
                {"severity": "high", "source": "events"},
            ],
            "cache": [{"severity": "low", "source": "metrics"}],
        },
    }
    md = correlated_markdown(correlated)
    # ranked components first, then the rest
    assert md.index("database") < md.index("cache")
    assert "2 finding(s)" in md and "events, logs" in md
    assert correlated_markdown({}) == "_No correlated findings._"


def test_store_set_title(tmp_path):
    from rca_tpu.store import InvestigationStore

    store = InvestigationStore(root=str(tmp_path))
    inv = store.create_investigation("untitled", namespace="ns")
    store.set_title(inv["id"], "database crash investigation")
    assert store.get_investigation(inv["id"])["title"] == (
        "database crash investigation"
    )


def test_cli_bench_small(capsys):
    rc, raw = run_cli(
        capsys, "bench", "--services", "120", "--roots", "1", "--seed", "0"
    )
    assert rc == 0
    out = json.loads(raw)
    assert out["n_services"] == 120
    assert out["latency_ms"] > 0
    assert isinstance(out["top1_hit"], bool)
    assert len(out["ranked"]) == 5
    # rca bench measures what rca analyze would run: the analyze-boundary
    # engine selection (sharded on the 8-device test mesh)
    assert out["engine"].startswith(("single", "sharded("))


def test_cli_train_tiny(capsys, tmp_path):
    ckpt = str(tmp_path / "w")
    # 3 iters is a mechanics smoke test — such a checkpoint can land a
    # hair below the defaults on the ship gate's holdout, so the save
    # must be forced (which also covers the flag)
    rc, raw = run_cli(
        capsys, "train", "--services", "48", "--cases", "4", "--iters", "3",
        "--seed", "0", "--out", ckpt, "--allow-unshippable",
    )
    assert rc == 0
    out = json.loads(raw)
    assert out["final_loss"] > 0 and out["initial_loss"] > 0
    assert out["checkpoint"] == ckpt
    assert "ships" in out["shippability"]
    # the checkpoint round-trips into an engine
    from rca_tpu.engine import GraphEngine
    from rca_tpu.engine.train import load_params

    engine = GraphEngine(params=load_params(ckpt))
    assert 0.0 < engine.params.decay < 1.0


def test_cli_stream_fixture(capsys):
    code = main([
        "stream", "--fixture", "50svc", "--ticks", "2", "--interval", "0",
        "--top", "3",
    ])
    assert code == 0
    lines = [json.loads(l) for l in
             capsys.readouterr().out.strip().splitlines()]
    assert len(lines) == 2
    assert lines[0]["tick"] == 1 and lines[1]["tick"] == 2
    assert lines[1]["changed_rows"] == 0  # frozen fixture: steady state
    assert lines[0]["ranked"][0]["component"].startswith("svc-")


def test_stream_tab_renders_with_fake_streamlit():
    """The Stream tab's logic is streamlit-free enough to drive with a
    scripted stand-in: start resets the session, a poll renders the ranked
    table, and history accumulates."""
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.ui.app import _render_stream_tab

    class FakeSt:
        def __init__(self, buttons):
            self.session_state = {}
            self._buttons = buttons  # label -> bool
            self.dataframes = []
            self.markdowns = []
            self.infos = []

        def button(self, label):
            return self._buttons.get(label, False)

        def checkbox(self, label, value=False, key=None):
            return False

        def dataframe(self, data):
            self.dataframes.append(data)

        def markdown(self, text):
            self.markdowns.append(text)

        def caption(self, text):
            pass

        def info(self, text):
            self.infos.append(text)

        def rerun(self):
            raise AssertionError("rerun must not fire without auto-poll")

    client = MockClusterClient(five_service_world())

    # no session yet -> the tab explains itself and renders nothing else
    st = FakeSt({})
    _render_stream_tab(st, client, NS)
    assert st.infos and not st.dataframes

    # start + poll in one pass: ranked table + history render
    st = FakeSt({"Start / reset stream": True, "Poll now": True})
    _render_stream_tab(st, client, NS)
    assert len(st.dataframes) == 2  # ranked + history
    ranked = st.dataframes[0]
    assert ranked[0]["component"] == "database"
    history = st.dataframes[1]
    assert history[0]["tick"] == 1 and history[0]["top"] == "database"

    # second poll reuses the session and extends history
    state_key = f"live-stream-{NS}"
    st2 = FakeSt({"Poll now": True})
    st2.session_state = st.session_state
    _render_stream_tab(st2, client, NS)
    assert st2.session_state[state_key]["history"][-1]["tick"] == 2

    # starting a stream for another namespace evicts the old session (each
    # one pins device-resident buffers)
    st3 = FakeSt({"Start / reset stream": True})
    st3.session_state = st2.session_state
    _render_stream_tab(st3, client, "other-ns")
    assert state_key not in st3.session_state
    assert "live-stream-other-ns" in st3.session_state


def test_cli_chat_persists_into_investigation(capsys, tmp_path):
    """A scriptable conversational loop: turn 1 creates the investigation,
    turn 2 resumes it with the accumulated findings feeding the prompt."""
    code, out = run_cli(
        capsys, "chat", "--fixture", "5svc", "--compact",
        "--log-dir", str(tmp_path), "--investigation", "new",
        "what is broken?",
    )
    assert code == 0
    turn1 = json.loads(out)
    iid = turn1["investigation_id"]

    code, out = run_cli(
        capsys, "chat", "--fixture", "5svc", "--compact",
        "--log-dir", str(tmp_path), "--investigation", iid,
        "what should I fix first?",
    )
    assert code == 0
    assert json.loads(out)["investigation_id"] == iid

    from rca_tpu.store import InvestigationStore

    inv = InvestigationStore(root=str(tmp_path)).get_investigation(iid)
    assert len(inv["conversation"]) == 4
    assert inv["accumulated_findings"]
    assert inv["next_actions"]

    # unknown id fails loudly
    code, out = run_cli(
        capsys, "chat", "--fixture", "5svc", "--compact",
        "--log-dir", str(tmp_path), "--investigation", "nope", "hi",
    )
    assert code == 1 and "no investigation" in out


def test_cli_report_markdown(capsys, tmp_path):
    out_file = tmp_path / "report.md"
    code, out = run_cli(
        capsys, "report", "--fixture", "5svc", "--log-dir", str(tmp_path),
        "--out", str(out_file),
    )
    assert code == 0
    assert json.loads(out)["written"] == str(out_file)
    md = out_file.read_text()
    assert "Root Cause Analysis Report" in md
    assert "database" in md

    # stdout mode
    code, out = run_cli(
        capsys, "report", "--fixture", "5svc", "--log-dir", str(tmp_path),
    )
    assert code == 0
    assert "Root Cause Analysis Report" in out


def test_reference_renderer_specs_golden():
    """One spec per reference renderer (VERDICT r3 item 8;
    /root/reference/components/visualization.py:8-764): comprehensive
    overview, metrics grouped usage, logs sunburst, traces dependency
    digraph, topology node-type coloring + edge legend, events donut —
    golden-checked from the 5svc comprehensive run (real Streamlit cannot
    run here, so the specs ARE the render contract)."""
    from rca_tpu.cluster.fixtures import NS, five_service_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.coordinator import RCACoordinator
    from rca_tpu.ui.render import (
        NODE_TYPE_COLORS,
        SEVERITY_COLORS,
        analysis_chart_series,
        analysis_viz_data,
        comprehensive_chart_series,
        topology_plot_data,
    )

    rec = RCACoordinator(MockClusterClient(five_service_world())).run_analysis(
        "comprehensive", NS
    )
    results = rec["results"]

    # -- _render_comprehensive_visualizations (:38) -------------------------
    comp = comprehensive_chart_series(results)
    titles = [c["title"] for c in comp]
    assert "Distribution of findings by severity" in titles
    assert "Findings by agent" in titles
    sev_chart = comp[0]
    assert sev_chart["colors"]  # severity color map rides the spec
    assert all(v in SEVERITY_COLORS.values()
               for v in sev_chart["colors"].values())
    agents_chart = next(c for c in comp if c["title"] == "Findings by agent")
    assert "logs" in agents_chart["data"] and "events" in agents_chart["data"]

    # -- _render_metrics_visualizations (:236) ------------------------------
    m_charts = analysis_chart_series(
        analysis_viz_data("metrics", results["metrics"])
    )
    grouped = [c for c in m_charts if c["kind"] == "bar_grouped"]
    assert grouped and set(grouped[0]["series"]) == {"cpu", "memory"}
    assert {t["value"] for t in grouped[0]["thresholds"]} == {80, 90}

    # -- _render_logs_visualizations (:376) — component/severity sunburst ---
    l_charts = analysis_chart_series(
        analysis_viz_data("logs", results["logs"])
    )
    sun = [c for c in l_charts if c["kind"] == "sunburst"]
    assert sun
    rows = sun[0]["data"]
    roots = [r for r in rows if r["parent"] == ""]
    leaves = [r for r in rows if r["parent"]]
    assert roots and leaves
    for leaf in leaves:
        sev = leaf["id"].rsplit("/", 1)[-1]
        assert leaf["color"] == SEVERITY_COLORS[sev]
        assert any(leaf["parent"] == r["id"] for r in roots)

    # -- _render_traces_visualizations (:516) — dependency digraph ----------
    t_charts = analysis_chart_series(
        analysis_viz_data("traces", results["traces"])
    )
    digraph = [c for c in t_charts if c["kind"] == "digraph"]
    assert digraph
    edges = digraph[0]["data"]
    assert {"source", "target", "source_severity", "target_severity"} <= set(
        edges[0]
    )
    # the 5svc fixture's trace deps include api-gateway -> backend
    assert any(
        e["source"] == "api-gateway" and e["target"] == "backend"
        for e in edges
    )

    # -- _render_topology_visualizations (:647) — node colors + legends -----
    topo_viz = analysis_viz_data("topology", results["topology"])
    plot = topology_plot_data(topo_viz["graph"])
    assert plot["nodes"] and all("color" in n for n in plot["nodes"])
    for n in plot["nodes"]:
        assert n["color"] == NODE_TYPE_COLORS.get(
            n["type"], NODE_TYPE_COLORS["unknown"]
        )
    assert set(plot["node_legend"]) == {n["type"] for n in plot["nodes"]}
    assert plot["edge_legend"]  # relation -> count
    assert sum(plot["edge_legend"].values()) == len(plot["edges"])

    # -- _render_events_visualizations (:809) — component-type donut --------
    e_charts = analysis_chart_series(
        analysis_viz_data("events", results["events"])
    )
    pies = [c for c in e_charts if c["kind"] == "pie"]
    assert pies and pies[0]["hole"] == 0.4
    assert "Pod" in pies[0]["data"]
