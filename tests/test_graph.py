"""Topology builder + array-based graph analysis tests."""

import numpy as np

from rca_tpu.cluster.fixtures import DEPENDENCIES, NS
from rca_tpu.cluster.generator import synthetic_cascade_world
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.features import extract_features
from rca_tpu.graph import (
    EdgeType,
    betweenness_centrality,
    build_typed_graph,
    find_cycles,
    isolated_nodes,
    longest_dependency_chain,
    service_dependency_edges,
)


def test_typed_graph_five_service(five_svc_client):
    snap = ClusterSnapshot.capture(five_svc_client, NS)
    g = build_typed_graph(snap)
    names = set(g.node_names)
    assert "service/database" in names and "workload/backend" in names
    assert "ingress/frontend-ingress" in names
    rel = {
        (g.node_names[int(s)], g.node_names[int(d)], int(t))
        for s, d, t in zip(g.edge_src, g.edge_dst, g.edge_types)
    }
    # service selects its workload
    assert ("service/backend", "workload/backend", int(EdgeType.SELECTS)) in rel
    # ingress routes to frontend
    assert ("ingress/frontend-ingress", "service/frontend", int(EdgeType.ROUTES)) in rel
    # env-DNS inference: backend depends on database
    assert ("workload/backend", "service/database", int(EdgeType.DEPENDS_ON)) in rel
    # missing secret reference recorded (api-gateway envFrom nonexistent secret)
    assert any(
        m["missing"] == "api-gateway-secrets" for m in g.missing_refs
    )


def test_service_dependency_condensation(five_svc_client):
    snap = ClusterSnapshot.capture(five_svc_client, NS)
    fs = extract_features(snap)
    src, dst = service_dependency_edges(snap, fs)
    sidx = {n: i for i, n in enumerate(fs.service_names)}
    pairs = set(zip(src.tolist(), dst.tolist()))
    # the fixture's full dependency map must be present (traces + env union)
    for a, deps in DEPENDENCIES.items():
        for b in deps:
            assert (sidx[a], sidx[b]) in pairs
    # no self edges
    assert all(s != d for s, d in pairs)


def test_cycles_and_chain():
    # 0->1->2->0 cycle plus 3->4->5 chain
    src = np.array([0, 1, 2, 3, 4], np.int32)
    dst = np.array([1, 2, 0, 4, 5], np.int32)
    cycles = find_cycles(6, src, dst)
    assert len(cycles) == 1
    assert set(cycles[0][:-1]) == {0, 1, 2}
    chain = longest_dependency_chain(6, src, dst)
    assert chain == [3, 4, 5]
    assert isolated_nodes(7, src, dst).tolist() == [6]


def test_longest_chain_scales():
    w = synthetic_cascade_world(300, n_roots=1, seed=5)
    snap = ClusterSnapshot.capture(MockClusterClient(w), "synthetic")
    fs = extract_features(snap)
    src, dst = service_dependency_edges(snap, fs)
    chain = longest_dependency_chain(fs.num_services, src, dst)
    assert len(chain) >= 3
    # chain edges actually exist
    pairs = set(zip(src.tolist(), dst.tolist()))
    for a, b in zip(chain, chain[1:]):
        assert (a, b) in pairs


def test_betweenness_hub():
    # star through node 2: 0->2,1->2,2->3,2->4
    src = np.array([0, 1, 2, 2], np.int32)
    dst = np.array([2, 2, 3, 4], np.int32)
    bc = betweenness_centrality(5, src, dst)
    assert bc[2] == bc.max() and bc[2] > 0
    # degree fallback beyond the gate
    bc2 = betweenness_centrality(5, src, dst, max_nodes=3)
    assert bc2[2] == bc2.max()


def test_betweenness_device_matches_python(monkeypatch):
    """The all-sources matmul Brandes (_bc_kernel, MXU path) must agree
    with the float64 Python loop on real cascade DAGs — force the device
    path on a small graph so the parity check stays CI-fast."""
    import rca_tpu.graph.analysis as ga
    from rca_tpu.cluster.generator import synthetic_cascade_arrays

    monkeypatch.setattr(ga, "_BC_DEVICE_MIN_NODES", 1)
    for seed in (0, 7):
        c = synthetic_cascade_arrays(150, n_roots=2, seed=seed)
        dev = ga.betweenness_centrality(150, c.dep_src, c.dep_dst)
        ref = ga._betweenness_python(150, c.dep_src, c.dep_dst)
        np.testing.assert_allclose(dev, ref, atol=1e-6)
    # a graph WITH a cycle (BFS levels still well-defined per source)
    src = np.array([0, 1, 2, 2, 3], np.int32)
    dst = np.array([1, 2, 0, 3, 4], np.int32)
    dev = ga.betweenness_centrality(5, src, dst)
    ref = ga._betweenness_python(5, src, dst)
    np.testing.assert_allclose(dev, ref, atol=1e-6)
