"""Test-environment parity: signature-level client conformance and the
kind-cluster manifest generator (dry-run, hermetic)."""

import inspect
import json
import subprocess
import sys

from rca_tpu.cluster import CLUSTER_CLIENT_METHODS, MockClusterClient
from rca_tpu.cluster.k8s_client import K8sApiClient


def test_signature_conformance_mock_vs_real():
    """Same parameter names in the same order for every protocol method —
    the reference's get_pod_logs skew (SURVEY.md §2.6) is structurally
    impossible."""
    for m in CLUSTER_CLIENT_METHODS:
        mock_params = list(
            inspect.signature(getattr(MockClusterClient, m)).parameters
        )
        real_params = list(
            inspect.signature(getattr(K8sApiClient, m)).parameters
        )
        assert mock_params == real_params, (
            f"{m}: mock{mock_params} != real{real_params}"
        )


def test_setup_cluster_dry_run_manifests():
    sys.path.insert(0, "tools")
    try:
        import setup_test_cluster as stc
    finally:
        sys.path.pop(0)

    manifests = stc.build_manifests()
    by_kind = {}
    for m in manifests:
        by_kind.setdefault(m["kind"], []).append(m)
    assert len(by_kind["Deployment"]) == 5
    assert len(by_kind["Service"]) == 5
    assert len(by_kind["NetworkPolicy"]) == 1

    deployments = {
        d["metadata"]["name"]: d for d in by_kind["Deployment"]
    }
    # injected faults match the hermetic fixture's world
    db_cmd = " ".join(
        deployments["database"]["spec"]["template"]["spec"]["containers"][0]
        ["command"]
    )
    assert "exit 1" in db_cmd
    gw_cmd = " ".join(
        deployments["api-gateway"]["spec"]["template"]["spec"]["containers"]
        [0]["command"]
    )
    assert "REQUIRED_API_KEY" in gw_cmd
    rs = deployments["resource-service"]["spec"]["template"]["spec"]
    assert rs["volumes"][0]["emptyDir"] == {"medium": "Memory"}
    assert (
        rs["containers"][0]["resources"]["limits"]["memory"] == "128Mi"
    )
    np_from = by_kind["NetworkPolicy"][0]["spec"]["ingress"][0]["from"][0]
    assert np_from["podSelector"]["matchLabels"]["app"] == (
        "non-existent-service"
    )

    # expected-findings oracle covers every injected fault component
    comps = {e["component"] for e in stc.expected_findings()}
    assert comps >= {
        "database", "api-gateway", "backend", "resource-service",
    }


def test_setup_cluster_dry_run_cli():
    out = subprocess.run(
        [sys.executable, "tools/setup_test_cluster.py", "--dry-run"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0
    assert "api-gateway" in out.stdout
    assert "expected findings" in out.stderr


def test_mock_and_manifests_agree_on_fault_roots():
    """The hermetic fixture and the live-cluster manifests model the same
    faulted world — analyzers can be validated against either."""
    sys.path.insert(0, "tools")
    try:
        import setup_test_cluster as stc
    finally:
        sys.path.pop(0)
    from rca_tpu.cluster.fixtures import five_service_world

    world = five_service_world()
    fixture_faults = set(world.ground_truth["faults"])
    manifest_comps = {
        e["component"] for e in stc.expected_findings()
        if e["component"] != "backend-network-policy"
    }
    assert fixture_faults == manifest_comps
