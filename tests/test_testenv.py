"""Test-environment parity: signature-level client conformance and the
kind-cluster manifest generator (dry-run, hermetic)."""

import inspect
import json
import os
import subprocess
import sys

from rca_tpu.cluster import CLUSTER_CLIENT_METHODS, MockClusterClient
from rca_tpu.cluster.k8s_client import K8sApiClient

from tests.conftest import import_setup_tool as _stc  # noqa: E402


def test_signature_conformance_mock_vs_real():
    """Same parameter names in the same order for every protocol method —
    the reference's get_pod_logs skew (SURVEY.md §2.6) is structurally
    impossible."""
    for m in CLUSTER_CLIENT_METHODS:
        mock_params = list(
            inspect.signature(getattr(MockClusterClient, m)).parameters
        )
        real_params = list(
            inspect.signature(getattr(K8sApiClient, m)).parameters
        )
        assert mock_params == real_params, (
            f"{m}: mock{mock_params} != real{real_params}"
        )


def test_setup_cluster_dry_run_manifests():
    stc = _stc()
    manifests = stc.build_manifests()
    by_kind = {}
    for m in manifests:
        by_kind.setdefault(m["kind"], []).append(m)
    assert len(by_kind["Deployment"]) == 5
    assert len(by_kind["Service"]) == 5
    assert len(by_kind["NetworkPolicy"]) == 1

    deployments = {
        d["metadata"]["name"]: d for d in by_kind["Deployment"]
    }
    # injected faults match the hermetic fixture's world
    db_cmd = " ".join(
        deployments["database"]["spec"]["template"]["spec"]["containers"][0]
        ["command"]
    )
    assert "exit 1" in db_cmd
    gw_cmd = " ".join(
        deployments["api-gateway"]["spec"]["template"]["spec"]["containers"]
        [0]["command"]
    )
    assert "REQUIRED_API_KEY" in gw_cmd
    rs = deployments["resource-service"]["spec"]["template"]["spec"]
    assert rs["volumes"][0]["emptyDir"] == {"medium": "Memory"}
    assert (
        rs["containers"][0]["resources"]["limits"]["memory"] == "128Mi"
    )
    np_from = by_kind["NetworkPolicy"][0]["spec"]["ingress"][0]["from"][0]
    assert np_from["podSelector"]["matchLabels"]["app"] == (
        "non-existent-service"
    )

    # expected-findings oracle covers every injected fault component
    comps = {e["component"] for e in stc.expected_findings()}
    assert comps >= {
        "database", "api-gateway", "backend", "resource-service",
    }


def test_setup_cluster_dry_run_cli():
    out = subprocess.run(
        [sys.executable, "tools/setup_test_cluster.py", "--dry-run"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0
    assert "api-gateway" in out.stdout
    assert "expected findings" in out.stderr


def test_mock_and_manifests_agree_on_fault_roots():
    """The hermetic fixture and the live-cluster manifests model the same
    faulted world — analyzers can be validated against either."""
    stc = _stc()
    from rca_tpu.cluster.fixtures import five_service_world

    world = five_service_world()
    fixture_faults = set(world.ground_truth["faults"])
    manifest_comps = {
        e["component"] for e in stc.expected_findings()
        if e["component"] != "backend-network-policy"
    }
    assert fixture_faults == manifest_comps


def test_oom_chain_manifests_shape():
    """BASELINE.md row 3 dry-run: ~200 pods, one OOMKill root whose fill
    EXCEEDS its memory limit, a connected dependency tree via PARENT_URL
    env DNS, and worker nodes so kubelet's 110-pod cap cannot bite."""
    from rca_tpu.cluster.oomchain import OOM_NS, OOM_ROOT, oom_chain_topology

    stc = _stc()
    manifests = stc.build_oom_chain_manifests(200)
    by_kind = {}
    for m in manifests:
        by_kind.setdefault(m["kind"], []).append(m)
    deployments = {d["metadata"]["name"]: d for d in by_kind["Deployment"]}
    services, parent, replicas = oom_chain_topology(200)

    assert set(deployments) == set(services)
    assert {s["metadata"]["name"] for s in by_kind["Service"]} == set(services)
    total_pods = sum(
        d["spec"]["replicas"] for d in deployments.values()
    )
    assert 190 <= total_pods <= 200
    assert total_pods == sum(replicas.values())

    root = deployments[OOM_ROOT]["spec"]["template"]["spec"]
    cmd = " ".join(root["containers"][0]["command"])
    # the fill must EXCEED the limit (real OOMKill, not just pressure),
    # the hog must be PID 1 so the kill lands on the container, and the
    # root must SERVE during its warm window — otherwise the cascade
    # exists from deploy time instead of being OOM-driven
    assert "count=150" in cmd and "exec dd" in cmd
    assert "httpd" in cmd
    assert root["containers"][0]["resources"]["limits"]["memory"] == "128Mi"
    assert root["volumes"][0]["emptyDir"] == {"medium": "Memory"}

    # every victim's PARENT_URL names its topology parent; the tree is
    # connected to the root
    for svc, par in parent.items():
        env = {
            e["name"]: e["value"]
            for e in deployments[svc]["spec"]["template"]["spec"]
            ["containers"][0].get("env", [])
        }
        assert f"//{par}.{OOM_NS}." in env["PARENT_URL"], (svc, env)
    reached = {OOM_ROOT}
    frontier = [OOM_ROOT]
    children = {}
    for svc, par in parent.items():
        children.setdefault(par, []).append(svc)
    while frontier:
        nxt = children.get(frontier.pop(), [])
        reached.update(nxt)
        frontier.extend(nxt)
    assert reached == set(services)

    # kind topology: the 200-pod profile gets worker nodes
    cfg = stc.kind_config("oom-chain-200")
    roles = [n["role"] for n in cfg["nodes"]]
    assert roles.count("worker") >= 2
    assert stc.kind_config("five-service")["nodes"][0]["role"] == \
        "control-plane"


def test_oom_chain_mock_twin_measurement():
    """The hermetic twin of the row-3 config: 200 pods, the engine ranks
    the OOMKilled root above all 66 symptomatic victims, through the SAME
    measurement hook the live kind path records (KIND_r*.json shape)."""
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.oomchain import (
        OOM_NS,
        OOM_ROOT,
        measure_analyze,
        oom_chain_world,
    )

    world = oom_chain_world(200)
    assert world.ground_truth["n_pods"] == 200
    out = measure_analyze(MockClusterClient(world), OOM_NS, OOM_ROOT)
    assert out["status"] == "completed"
    assert out["backend"] == "jax", out["fallback_reason"]
    assert out["hit1"] is True, out["top5"]
    assert out["latency_warm_ms"] > 0
    assert out["latency_first_run_ms"] >= out["latency_warm_ms"] * 0.5
    # deterministic oracle agrees on the root service
    det = measure_analyze(
        MockClusterClient(world), OOM_NS, OOM_ROOT, backend="deterministic"
    )
    assert det["status"] == "completed"
    assert any(OOM_ROOT in c for c in det["top5"]), det["top5"]


def test_oom_chain_dry_run_cli():
    out = subprocess.run(
        [sys.executable, "tools/setup_test_cluster.py",
         "--profile", "oom-chain-200", "--dry-run"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0
    assert "cache" in out.stdout and "svc-000" in out.stdout
    assert "OOMKilled" in out.stderr  # oracle on stderr
