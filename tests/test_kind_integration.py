"""Opt-in end-to-end test against a live kind cluster.

Closes the loop the reference had (reference: setup_test_cluster.py:382-398 —
expected findings documented for the live faulted environment): applies the
manifests from ``tools/setup_test_cluster.py`` to a real kind cluster, waits
for the injected faults to manifest, runs the comprehensive analyzer through
the live ``K8sApiClient``, and asserts every component in the
``expected_findings()`` oracle is surfaced.

Opt-in because it needs Docker + kind + several minutes of wall clock:

    RCA_KIND_TEST=1 python -m pytest tests/test_kind_integration.py -v

Skipped automatically when ``RCA_KIND_TEST`` is unset or kind/kubectl/docker
are unavailable.  ``RCA_KIND_KEEP=1`` keeps the cluster afterwards for
interactive use (``python -m rca_tpu ui`` against it).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SETUP = os.path.join(REPO, "tools", "setup_test_cluster.py")

pytestmark = pytest.mark.skipif(
    not os.environ.get("RCA_KIND_TEST")
    or shutil.which("kind") is None
    or shutil.which("kubectl") is None
    or shutil.which("docker") is None,
    reason="live kind test is opt-in: set RCA_KIND_TEST=1 with "
    "docker+kind+kubectl installed",
)


def _sh(*cmd: str, timeout: int = 600) -> subprocess.CompletedProcess:
    return subprocess.run(
        list(cmd), capture_output=True, text=True, timeout=timeout
    )


@pytest.fixture(scope="module")
def kind_cluster():
    """Create (or reuse) the faulted kind cluster; tear down unless kept."""
    from tools.setup_test_cluster import CLUSTER_NAME, NAMESPACE

    rc = subprocess.call([sys.executable, SETUP])
    if rc != 0:
        pytest.fail(f"setup_test_cluster.py exited {rc}")
    # wait until the injected faults are observable: BOTH crashing workloads
    # (database exits 1 after ~30s; api-gateway exits on its missing env
    # var) must have restarted, then settle a bit longer so the slower
    # faults — backend's CPU spin crossing the utilization threshold and
    # resource-service's 90Mi memory fill — have manifested in kubectl-top
    # metrics before the analyzer runs
    restarts: dict = {}
    deadline = time.time() + 360
    while time.time() < deadline:
        out = _sh(
            "kubectl", "get", "pods", "-n", NAMESPACE,
            "-o", "jsonpath={range .items[*]}{.metadata.name} "
            "{.status.containerStatuses[0].restartCount}\n{end}",
        ).stdout
        restarts = {
            line.split()[0]: int(line.split()[1])
            for line in out.strip().splitlines()
            if len(line.split()) == 2
        }
        crashed = {
            prefix: any(
                name.startswith(prefix) and count >= 1
                for name, count in restarts.items()
            )
            for prefix in ("database", "api-gateway")
        }
        if all(crashed.values()):
            break
        time.sleep(10)
    else:
        pytest.fail(f"faults never manifested; pod restarts: {restarts}")
    time.sleep(60)  # metrics-server scrape interval for the slow faults
    yield NAMESPACE
    if not os.environ.get("RCA_KIND_KEEP"):
        subprocess.call([sys.executable, SETUP, "--delete"])


def test_analyzer_finds_injected_faults_on_live_cluster(kind_cluster):
    from rca_tpu.cluster.k8s_client import K8sApiClient
    from rca_tpu.coordinator import RCACoordinator
    from tools.setup_test_cluster import expected_findings

    client = K8sApiClient()
    assert client.is_connected(), "kind cluster not reachable via kubeconfig"

    coord = RCACoordinator(client, backend="deterministic")
    record = coord.run_analysis("comprehensive", kind_cluster)
    assert record["status"] == "completed"
    results = record["results"]

    flat = [
        f
        for res in results.values()
        if isinstance(res, dict)
        for f in res.get("findings", [])
    ]
    assert flat, "no findings at all against the faulted cluster"

    # per-oracle: some finding's COMPONENT must name the faulted workload
    # (substring over the concatenated blob would let 'backend' be satisfied
    # by the 'backend-network-policy' finding), and — where the fault has an
    # unambiguous signature — that finding's text must carry it
    signature_terms = {
        "database": ("crashloopbackoff", "restart", "exit"),
        "api-gateway": ("exit", "crash", "fail", "env"),
        "backend": ("cpu",),
        "resource-service": ("memory",),
        "backend-network-policy": ("selector", "ingress", "network"),
    }
    missed = []
    for oracle in expected_findings():
        want = oracle["component"].lower()
        matching = [
            f for f in flat
            if want in str(f.get("component", "")).lower()
            # exact-word guard: 'backend' must not match the policy object
            and (want != "backend"
                 or "network-policy" not in str(f.get("component", "")))
        ]
        terms = signature_terms[want]
        if not any(
            any(
                t in f"{f.get('issue', '')} {f.get('evidence', '')}".lower()
                for t in terms
            )
            for f in matching
        ):
            missed.append(oracle)
    assert not missed, (
        f"injected faults never surfaced with their signature: {missed}"
    )

    # the fused ranking must put one of the two hard-failing workloads
    # (database restart loop / api-gateway missing env) at the top
    roots = results.get("correlated", {}).get("root_causes", [])
    assert roots, "correlation produced no ranked root causes"
    top = roots[0]["component"].lower()
    assert any(name in top for name in ("database", "api-gateway")), (
        f"top root cause {top!r} is not one of the crashing workloads"
    )
