"""Opt-in end-to-end test against a live kind cluster.

Closes the loop the reference had (reference: setup_test_cluster.py:382-398 —
expected findings documented for the live faulted environment): applies the
manifests from ``tools/setup_test_cluster.py`` to a real kind cluster, waits
for the injected faults to manifest, runs the comprehensive analyzer through
the live ``K8sApiClient``, and asserts every component in the
``expected_findings()`` oracle is surfaced.

Opt-in because it needs Docker + kind + several minutes of wall clock:

    RCA_KIND_TEST=1 python -m pytest tests/test_kind_integration.py -v

Skipped automatically when ``RCA_KIND_TEST`` is unset or kind/kubectl/docker
are unavailable.  ``RCA_KIND_KEEP=1`` keeps the cluster afterwards for
interactive use (``python -m rca_tpu ui`` against it).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import time

import pytest

# the ONE import mechanism for tools/setup_test_cluster.py in tests (a
# dotted `from tools.setup_test_cluster import ...` would create a second,
# separate module object with duplicated import side effects)
from tests.conftest import import_setup_tool as _setup_tool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SETUP = os.path.join(REPO, "tools", "setup_test_cluster.py")

pytestmark = pytest.mark.skipif(
    not os.environ.get("RCA_KIND_TEST")
    or shutil.which("kind") is None
    or shutil.which("kubectl") is None
    or shutil.which("docker") is None,
    reason="live kind test is opt-in: set RCA_KIND_TEST=1 with "
    "docker+kind+kubectl installed",
)


def _sh(*cmd: str, timeout: int = 600) -> subprocess.CompletedProcess:
    return subprocess.run(
        list(cmd), capture_output=True, text=True, timeout=timeout
    )


@pytest.fixture(scope="module")
def kind_cluster():
    """Create (or reuse) the faulted kind cluster; tear down unless kept."""
    NAMESPACE = _setup_tool().NAMESPACE

    rc = subprocess.call([sys.executable, SETUP])
    if rc != 0:
        pytest.fail(f"setup_test_cluster.py exited {rc}")
    # wait until the injected faults are observable: BOTH crashing workloads
    # (database exits 1 after ~30s; api-gateway exits on its missing env
    # var) must have restarted, then settle a bit longer so the slower
    # faults — backend's CPU spin crossing the utilization threshold and
    # resource-service's 90Mi memory fill — have manifested in kubectl-top
    # metrics before the analyzer runs
    restarts: dict = {}
    deadline = time.time() + 360
    while time.time() < deadline:
        out = _sh(
            "kubectl", "get", "pods", "-n", NAMESPACE,
            "-o", "jsonpath={range .items[*]}{.metadata.name} "
            "{.status.containerStatuses[0].restartCount}\n{end}",
        ).stdout
        restarts = {
            line.split()[0]: int(line.split()[1])
            for line in out.strip().splitlines()
            if len(line.split()) == 2
        }
        crashed = {
            prefix: any(
                name.startswith(prefix) and count >= 1
                for name, count in restarts.items()
            )
            for prefix in ("database", "api-gateway")
        }
        if all(crashed.values()):
            break
        time.sleep(10)
    else:
        pytest.fail(f"faults never manifested; pod restarts: {restarts}")
    time.sleep(60)  # metrics-server scrape interval for the slow faults
    yield NAMESPACE
    if not os.environ.get("RCA_KIND_KEEP"):
        # scope the teardown to this fixture's cluster (bare --delete now
        # removes EVERY profile's cluster, including a concurrently-running
        # oom-chain one)
        subprocess.call(
            [sys.executable, SETUP, "--profile", "five-service", "--delete"]
        )


def test_analyzer_finds_injected_faults_on_live_cluster(kind_cluster):
    from rca_tpu.cluster.k8s_client import K8sApiClient
    from rca_tpu.coordinator import RCACoordinator

    expected_findings = _setup_tool().expected_findings
    client = K8sApiClient()
    assert client.is_connected(), "kind cluster not reachable via kubeconfig"

    coord = RCACoordinator(client, backend="deterministic")
    record = coord.run_analysis("comprehensive", kind_cluster)
    assert record["status"] == "completed"
    results = record["results"]

    flat = [
        f
        for res in results.values()
        if isinstance(res, dict)
        for f in res.get("findings", [])
    ]
    assert flat, "no findings at all against the faulted cluster"

    # per-oracle: some finding's COMPONENT must name the faulted workload
    # (substring over the concatenated blob would let 'backend' be satisfied
    # by the 'backend-network-policy' finding), and — where the fault has an
    # unambiguous signature — that finding's text must carry it
    signature_terms = {
        "database": ("crashloopbackoff", "restart", "exit"),
        "api-gateway": ("exit", "crash", "fail", "env"),
        "backend": ("cpu",),
        "resource-service": ("memory",),
        "backend-network-policy": ("selector", "ingress", "network"),
    }
    missed = []
    for oracle in expected_findings():
        want = oracle["component"].lower()
        matching = [
            f for f in flat
            if want in str(f.get("component", "")).lower()
            # exact-word guard: 'backend' must not match the policy object
            and (want != "backend"
                 or "network-policy" not in str(f.get("component", "")))
        ]
        terms = signature_terms[want]
        if not any(
            any(
                t in f"{f.get('issue', '')} {f.get('evidence', '')}".lower()
                for t in terms
            )
            for f in matching
        ):
            missed.append(oracle)
    assert not missed, (
        f"injected faults never surfaced with their signature: {missed}"
    )

    # the fused ranking must put one of the two hard-failing workloads
    # (database restart loop / api-gateway missing env) at the top
    roots = results.get("correlated", {}).get("root_causes", [])
    assert roots, "correlation produced no ranked root causes"
    top = roots[0]["component"].lower()
    assert any(name in top for name in ("database", "api-gateway")), (
        f"top root cause {top!r} is not one of the crashing workloads"
    )


@pytest.fixture(scope="module")
def oom_chain_cluster():
    """Deploy the BASELINE row-3 oom-chain-200 profile (its own kind
    cluster — the profile needs worker nodes the five-service cluster
    does not have) and wait for the OOMKill loop via the tool's canonical
    wait protocol."""
    from rca_tpu.cluster.oomchain import OOM_NS, OOM_ROOT

    stc = _setup_tool()
    rc = subprocess.call(
        [sys.executable, SETUP, "--profile", "oom-chain-200"]
    )
    if rc != 0:
        pytest.fail(f"setup_test_cluster.py --profile oom-chain-200 "
                    f"exited {rc}")
    # the root warms ~20s, then the 150Mi fill OOMs against 128Mi; the
    # shared wait protocol insists on the OOMKilled reason, then settles
    # so the cascade propagates a few 5s probe cycles down the tree
    if not stc.wait_for_fault(OOM_NS, OOM_ROOT,
                              require_reason="OOMKilled"):
        pytest.fail("root never OOMKilled within the deadline")
    yield OOM_NS
    if not os.environ.get("RCA_KIND_KEEP"):
        subprocess.call(
            [sys.executable, SETUP, "--profile", "oom-chain-200",
             "--delete"]
        )


def test_oom_chain_200_measurement(oom_chain_cluster):
    """BASELINE.md row 3 measured live: end-to-end analyze latency +
    hit@1 on the 200-pod OOMKill chain, recorded through the SAME
    run_measurement hook the CLI's --measure uses (one recording format,
    no drift) as KIND_r03.json."""
    import json

    from rca_tpu.cluster.oomchain import OOM_ROOT

    stc = _setup_tool()
    # distinct path: the committed KIND_r03.json is the hermetic-mock
    # placeholder BASELINE.md quotes; a live run must not silently
    # overwrite it
    out_path = os.path.join(REPO, "KIND_r03_live.json")
    # the fixture already waited for the OOMKill + cascade settle
    rc = stc.run_measurement(
        oom_chain_cluster, OOM_ROOT, out_path,
        "oom_chain_200_analyze", OOM_ROOT, wait=False,
    )
    assert rc == 0
    result = json.load(open(out_path))
    assert result["environment"] == "live-kind"
    assert result["backend"] == "jax", result["fallback_reason"]
    assert result["hit1"] is True, result["top5"]
