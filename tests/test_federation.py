"""Cross-process serve federation (ISSUE 15).

Four layers, cheapest first:

- pure units on a FAKE clock: the lease/heartbeat state machine
  (grant, renew, miss-one-keep-alive, expire, stale-lease rejection),
  the rendezvous hash ring's remap bound, the frame codec, the config
  knobs, and the procs seam;
- the CONTROL PLANE against in-process fake workers speaking the real
  wire protocol over loopback sockets: routing stickiness, the
  exactly-once property with 8 submit threads racing a worker kill,
  stale-response drops from a hung worker that answers late, the
  rejoin path, and the no-fleet degradation ladder;
- REAL worker processes: the federation selftest (pool-vs-federation
  bit parity) and the SIGKILL kill-chaos gate — the acceptance
  criteria, run small;
- the TLS+authn gateway OVER a federation plane lives in
  tests/test_gateway.py (the front-door contract is the gateway's).
"""

from __future__ import annotations

import math
import time

import numpy as np
import pytest

from rca_tpu.serve.federation import (
    FED_FAULT_CLASSES,
    FederationPlane,
    HashRing,
    LeaseTable,
    graph_route_key,
)
from rca_tpu.serve.fedwire import (
    FrameConn,
    FrameError,
    PROTO,
    decode_request_kwargs,
    encode_request,
)
from rca_tpu.serve.request import ServeRequest
from rca_tpu.util.net import make_client_socket
from rca_tpu.util.threads import make_lock, make_thread, spawn


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _req(tenant="t", n=8, seed=0, **kw) -> ServeRequest:
    rng = np.random.default_rng(seed)
    feats = rng.random((n, 14), dtype=np.float32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    return ServeRequest(
        tenant=tenant, features=feats, dep_src=src, dep_dst=dst, **kw
    )


# ---------------------------------------------------------------------------
# Lease/heartbeat state machine (fake clock — the satellite checklist)
# ---------------------------------------------------------------------------


def test_lease_grant_and_renew():
    clock = FakeClock()
    table = LeaseTable(heartbeat_s=1.0, lease_misses=3, clock=clock)
    lease = table.grant(0)
    assert table.alive(0)
    assert table.ttl_s == 3.0
    clock.advance(1.0)
    assert table.renew(0, lease.lease_id)
    clock.advance(2.9)           # 2.9 < ttl since renewal
    assert table.alive(0)
    assert lease.renewals == 1


def test_lease_miss_one_heartbeat_keeps_alive():
    """ONE late heartbeat must never kill a worker: the TTL is
    heartbeat × misses (>= 2 enforced)."""
    clock = FakeClock()
    table = LeaseTable(heartbeat_s=1.0, lease_misses=3, clock=clock)
    lease = table.grant(7)
    clock.advance(2.5)           # missed two beats, inside ttl=3
    assert table.alive(7)
    assert table.renew(7, lease.lease_id)   # late renewal still lands
    assert table.alive(7)


def test_lease_expires_after_misses():
    clock = FakeClock()
    table = LeaseTable(heartbeat_s=1.0, lease_misses=3, clock=clock)
    lease = table.grant(1)
    clock.advance(3.0)
    assert not table.alive(1)
    assert table.expired_workers() == [(1, 0.0)]
    # an EXPIRED lease cannot be renewed — the holder must re-hello
    assert not table.renew(1, lease.lease_id)


def test_rejoin_with_stale_lease_rejected():
    """A worker declared dead holds a STALE lease: renewal against it
    is refused even before expiry of the replacement, and only a fresh
    grant (the re-hello path) restores liveness."""
    clock = FakeClock()
    table = LeaseTable(heartbeat_s=1.0, lease_misses=3, clock=clock)
    old = table.grant(2)
    fresh = table.grant(2)       # re-grant supersedes
    assert not table.renew(2, old.lease_id)
    assert table.renew(2, fresh.lease_id)
    assert old.lease_id != fresh.lease_id


def test_lease_table_rejects_bad_params():
    with pytest.raises(ValueError):
        LeaseTable(heartbeat_s=0.0, lease_misses=3)
    with pytest.raises(ValueError):
        # one late heartbeat must never kill a worker
        LeaseTable(heartbeat_s=1.0, lease_misses=1)


# ---------------------------------------------------------------------------
# Consistent-hash ring: remap bound (the satellite checklist)
# ---------------------------------------------------------------------------


def _keys(k: int):
    return [f"{64 + i}/14/{128 + i}/d{i:05x}" for i in range(k)]


def test_ring_deterministic_and_total():
    ring = HashRing()
    for n in range(4):
        ring.add(n)
    for key in _keys(32):
        assert ring.owner(key) == ring.owner(key)
        assert sorted(ring.ranked(key)) == [0, 1, 2, 3]


@pytest.mark.parametrize("dead", [0, 1, 2])
def test_ring_remap_bound_when_one_of_n_dies(dead):
    """Kill any one of N=3 workers over K=64 keys: the keys that move
    are EXACTLY the dead worker's (survivors' keys never reshuffle —
    the rendezvous property delta-scatter stickiness rides on), and the
    moved count stays <= ceil(K/N).  Deterministic: the ring is seeded
    hashing, the key set is fixed."""
    K, N = 64, 3
    ring = HashRing()
    for n in range(N):
        ring.add(n)
    keys = _keys(K)
    before = {k: ring.owner(k) for k in keys}
    ring.remove(dead)
    after = {k: ring.owner(k) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    # only the dead worker's keys moved...
    assert all(before[k] == dead for k in moved)
    assert all(after[k] != dead for k in keys)
    # ...every one of its keys moved somewhere live...
    assert len(moved) == sum(1 for k in keys if before[k] == dead)
    # ...and the handoff is bounded
    assert len(moved) <= math.ceil(K / N)


def test_ring_rejoin_restores_exact_ownership():
    """Adding a node back restores the EXACT pre-death ownership map —
    a bounced worker reclaims precisely its old buckets (hot graphs
    return to their resident bases)."""
    ring = HashRing()
    for n in range(3):
        ring.add(n)
    keys = _keys(48)
    before = {k: ring.owner(k) for k in keys}
    ring.remove(1)
    ring.add(1)
    assert {k: ring.owner(k) for k in keys} == before


def test_graph_route_key_matches_graph_identity():
    a, b = _req(seed=1), _req(seed=1)
    assert graph_route_key(a.graph_key) == graph_route_key(b.graph_key)
    c = _req(seed=2, n=9)
    assert graph_route_key(a.graph_key) != graph_route_key(c.graph_key)


# ---------------------------------------------------------------------------
# Frame codec
# ---------------------------------------------------------------------------


def test_frame_roundtrip_over_socketpair():
    import socket as _socket_mod  # stdlib pair for a loopback-free test

    a, b = _socket_mod.socketpair()
    ca, cb = FrameConn(a, "a"), FrameConn(b, "b")
    assert ca.send({"t": "hello", "proto": PROTO, "worker_id": 3})
    msg = cb.recv()
    assert msg == {"t": "hello", "proto": PROTO, "worker_id": 3}
    ca.close()
    assert cb.recv() is None     # clean EOF = peer death, not an error
    cb.close()


def test_frame_oversized_inbound_poisons_loudly():
    import socket as _socket_mod
    import struct

    a, b = _socket_mod.socketpair()
    cb = FrameConn(b, "b")
    a.sendall(struct.pack(">I", 1 << 31))
    with pytest.raises(FrameError):
        cb.recv()
    a.close()
    cb.close()


def test_request_frame_roundtrip_bit_exact():
    req = _req(tenant="acme", k=3, seed=5)
    msg = encode_request(req)
    kwargs = decode_request_kwargs(msg)
    twin = ServeRequest(**kwargs)
    assert np.array_equal(twin.features, req.features)
    assert twin.features.dtype == np.float32
    assert np.array_equal(twin.dep_src, req.dep_src)
    assert twin.tenant == "acme" and twin.k == 3
    # same graph identity ⇒ same ring owner on any worker set
    assert twin.graph_key == req.graph_key


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------


def test_fed_env_knobs_round_trip(monkeypatch):
    from rca_tpu.config import (
        fed_heartbeat_s,
        fed_lease_misses,
        fed_window,
        fed_workers,
    )

    monkeypatch.setenv("RCA_FED_WORKERS", "5")
    monkeypatch.setenv("RCA_FED_HEARTBEAT_S", "0.25")
    monkeypatch.setenv("RCA_FED_LEASE_MISSES", "4")
    monkeypatch.setenv("RCA_FED_WINDOW", "16")
    assert fed_workers() == 5
    assert fed_heartbeat_s() == 0.25
    assert fed_lease_misses() == 4
    assert fed_window() == 16
    monkeypatch.setenv("RCA_FED_LEASE_MISSES", "1")
    with pytest.raises(ValueError):
        fed_lease_misses()


# ---------------------------------------------------------------------------
# Procs seam
# ---------------------------------------------------------------------------


def test_procs_spawn_capture_and_join():
    import sys

    from rca_tpu.util.procs import spawn_worker

    w = spawn_worker("echo", [
        sys.executable, "-c",
        "import sys; print('out-line'); print('err-line', file=sys.stderr)",
    ])
    assert w.join(30.0) == 0
    time.sleep(0.1)              # let the reader threads drain EOF
    out, err = w.output()
    assert "out-line" in out and "err-line" in err
    assert not w.alive()


def test_procs_kill_ladder():
    import sys

    from rca_tpu.util.procs import spawn_worker

    w = spawn_worker("sleeper", [
        sys.executable, "-c", "import time; time.sleep(600)",
    ])
    assert w.alive()
    rc = w.kill()
    assert rc is not None and rc != 0
    assert not w.alive()
    # idempotent on a dead child
    assert w.terminate() == rc


# ---------------------------------------------------------------------------
# Control plane vs FAKE workers (real wire protocol, no processes)
# ---------------------------------------------------------------------------


class FakeWorker:
    """An in-process worker speaking the real protocol over a loopback
    socket.  ``behavior``:

    - ``"serve"``: heartbeat + answer every request ok;
    - ``"hold"``: heartbeat, but HOLD requests unanswered (until
      :meth:`release`, which answers them late — the stale-drop case);
    - ``"mute"``: never heartbeat after joining (lease must expire).
    """

    def __init__(self, worker_id, plane, behavior="serve",
                 heartbeat_s=0.05):
        self.worker_id = worker_id
        self.behavior = behavior
        self.heartbeat_s = heartbeat_s
        self.lease_id = None
        self.held = []
        self.served = 0
        self.rejected = 0
        self._lock = make_lock("FakeWorker._lock")
        sock = make_client_socket(
            f"fake{worker_id}", plane.host, plane.port
        )
        self.conn = FrameConn(sock, name=f"fake{worker_id}")
        self.conn.send({
            "t": "hello", "proto": PROTO, "worker_id": worker_id,
            "pid": 0, "engine": "fake",
        })
        self._reader = spawn(
            self._read_loop, name=f"fake{worker_id}-read", daemon=True,
        )
        self._hb = spawn(
            self._hb_loop, name=f"fake{worker_id}-hb", daemon=True,
        )

    def _answer(self, request_id):
        self.conn.send({
            "t": "resp", "request_id": request_id, "status": "ok",
            "ranked": [{"component": f"svc-{self.worker_id}",
                        "score": 1.0}],
            "batch_size": 1, "engine": "fake",
        })

    def _read_loop(self):
        while True:
            try:
                msg = self.conn.recv()
            except (FrameError, OSError):
                return
            if msg is None:
                return
            t = msg.get("t")
            if t == "lease":
                with self._lock:
                    self.lease_id = msg["lease_id"]
            elif t == "reject":
                with self._lock:
                    self.rejected += 1
                    self.lease_id = None
                self.conn.send({
                    "t": "hello", "proto": PROTO,
                    "worker_id": self.worker_id, "pid": 0,
                    "engine": "fake",
                })
            elif t == "req":
                if self.behavior == "hold":
                    with self._lock:
                        self.held.append(msg["request_id"])
                else:
                    self._answer(msg["request_id"])
                    self.served += 1
            elif t == "drain":
                self.conn.send({"t": "drained"})

    def _hb_loop(self):
        seq = 0
        while not self.conn.closed:
            time.sleep(self.heartbeat_s)
            with self._lock:
                lease = self.lease_id
            if lease is None or self.behavior == "mute":
                continue
            seq += 1
            if not self.conn.send({
                "t": "hb", "worker_id": self.worker_id,
                "lease_id": lease, "seq": seq,
            }):
                return

    def release_held(self):
        """Answer every held request LATE (after a reroute these must
        be dropped as stale, never double-completed)."""
        with self._lock:
            held, self.held = self.held, []
        for rid in held:
            self._answer(rid)

    def close(self):
        self.conn.close()


def _plane(workers=0, **kw):
    kw.setdefault("heartbeat_s", 0.05)
    kw.setdefault("lease_misses", 3)
    plane = FederationPlane(
        workers=max(workers, 1), spawn_workers=False, **kw
    )
    plane.start()
    return plane


def _join(plane, n, behaviors=None, **kw):
    fakes = [
        FakeWorker(i, plane,
                   behavior=(behaviors or {}).get(i, "serve"), **kw)
        for i in range(n)
    ]
    assert plane.wait_ready(n, timeout_s=10.0)
    return fakes


def test_plane_routes_sticky_by_graph_digest():
    plane = _plane()
    fakes = _join(plane, 3)
    try:
        reqs = [_req(seed=9) for _ in range(6)]       # ONE graph
        for r in reqs:
            plane.submit(r)
        assert all(r.result(10.0).ok for r in reqs)
        # one bucket ⇒ one worker served all of it (ring stickiness)
        servers = {r.response.ranked[0]["component"] for r in reqs}
        assert len(servers) == 1
        # a different graph may land elsewhere, deterministically
        other = [_req(seed=10, n=12) for _ in range(3)]
        for r in other:
            plane.submit(r)
        assert all(r.result(10.0).ok for r in other)
        assert len({
            r.response.ranked[0]["component"] for r in other
        }) == 1
    finally:
        plane.stop()
        for f in fakes:
            f.close()


def test_exactly_once_eight_threads_racing_worker_kill():
    """The satellite checklist's exactly-once property: 8 wire threads
    submit while a worker dies mid-storm — every request reaches a
    terminal state and ``double_completions == 0``."""
    plane = _plane()
    fakes = _join(plane, 3)
    all_reqs = []
    lock = make_lock("test.reqs_lock")
    try:
        def submitter(w):
            for i in range(12):
                r = _req(tenant=f"t{w}", seed=(w * 31 + i) % 7,
                         n=8 + (i % 3))
                with lock:
                    all_reqs.append(r)
                plane.submit(r)
                if w == 0 and i == 4:
                    fakes[1].close()          # process death mid-storm
        threads = [
            make_thread(submitter, name=f"race-{w}", daemon=True,
                        args=(w,))
            for w in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        responses = [r.result(15.0) for r in all_reqs]
        assert all(r.status in ("ok", "shed", "degraded", "error")
                   for r in responses)
        assert plane.sink.double_completions == 0
        # the dead worker's keys were reclaimed and re-placed
        down = [e for e in plane.events if e["event"] == "worker_down"]
        assert down and down[0]["worker_id"] == 1
    finally:
        plane.stop()
        for f in fakes:
            f.close()


def test_hung_worker_late_answers_dropped_as_stale():
    """worker_hang: heartbeats stop, socket stays open, the worker
    still ANSWERS after being declared dead — those answers must be
    dropped as stale (counted), never double-completed, and the
    rerouted copies serve the caller."""
    plane = _plane()
    fakes = _join(plane, 2, behaviors={0: "hold"})
    try:
        # force every request onto the holding worker by joining it
        # alone first? simpler: submit a spread and act on whichever
        # landed on worker 0 (ring is deterministic but seed-dependent)
        reqs = [_req(seed=s, n=8 + s % 4) for s in range(8)]
        for r in reqs:
            plane.submit(r)
        time.sleep(0.3)          # routed; worker 0 holds its share
        held_n = len(fakes[0].held)
        fakes[0].behavior = "mute"      # heartbeats stop → hang
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if any(e["event"] == "worker_down"
                   and e.get("class") == "worker_hang"
                   for e in plane.events):
                break
            time.sleep(0.05)
        responses = [r.result(15.0) for r in reqs]
        assert all(r.status in ("ok", "degraded") for r in responses)
        # the hung worker wakes up and answers LATE
        fakes[0].release_held()
        time.sleep(0.5)
        assert plane.sink.double_completions == 0
        if held_n:
            assert plane.stale_responses >= held_n
            assert plane.reroutes >= held_n
    finally:
        plane.stop()
        for f in fakes:
            f.close()


def test_mute_worker_expires_and_rejoins_with_fresh_lease():
    """The full hang→expire→stale-reject→re-hello→rejoin cycle against
    the REAL wire protocol (fake worker, real plane)."""
    plane = _plane()
    fakes = _join(plane, 2)
    try:
        fakes[0].behavior = "mute"
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if any(e["event"] == "worker_down"
                   and e["worker_id"] == 0 for e in plane.events):
                break
            time.sleep(0.05)
        assert 0 not in plane.live_workers()
        fakes[0].behavior = "serve"     # wakes: stale hb → reject →
        deadline = time.monotonic() + 10.0   # re-hello → fresh lease
        while time.monotonic() < deadline:
            if any(e["event"] == "rejoin" and e["worker_id"] == 0
                   for e in plane.events):
                break
            time.sleep(0.05)
        assert 0 in plane.live_workers()
        assert fakes[0].rejected >= 1   # the stale lease WAS rejected
        assert any(e["event"] == "stale_lease_rejected"
                   or e["event"] == "rejoin" for e in plane.events)
    finally:
        plane.stop()
        for f in fakes:
            f.close()


def test_no_fleet_rides_ladder_instead_of_hanging():
    plane = _plane()
    try:
        req = _req(seed=3)
        plane.submit(req)
        resp = req.result(10.0)
        assert resp.status == "error"   # no last-known: honest error
        assert "no_worker" in resp.detail or "stopped" in resp.detail
    finally:
        plane.stop()


def test_coordinator_partition_drops_frames_then_heals():
    plane = _plane()
    fakes = _join(plane, 2)
    try:
        ttl = plane.leases.ttl_s
        plane.partition(0, for_s=ttl * 3)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if any(e["event"] == "worker_down"
                   and e.get("class") == "coordinator_partition"
                   for e in plane.events):
                break
            time.sleep(0.05)
        assert any(e.get("class") == "coordinator_partition"
                   for e in plane.events if e["event"] == "worker_down")
        deadline = time.monotonic() + 15.0      # heal → rejoin
        while time.monotonic() < deadline:
            if any(e["event"] == "rejoin" and e["worker_id"] == 0
                   for e in plane.events):
                break
            time.sleep(0.05)
        assert 0 in plane.live_workers()
    finally:
        plane.stop()
        for f in fakes:
            f.close()


def test_plane_stop_resolves_everything():
    plane = _plane()
    fakes = _join(plane, 1, behaviors={0: "hold"})
    try:
        reqs = [_req(seed=s) for s in range(4)]
        for r in reqs:
            plane.submit(r)
        time.sleep(0.2)
    finally:
        plane.stop(timeout=2.0)
        for f in fakes:
            f.close()
    assert all(r.done() for r in reqs)


# ---------------------------------------------------------------------------
# Real worker processes (the acceptance gates, run small)
# ---------------------------------------------------------------------------


def test_federation_selftest_two_workers_bit_parity():
    from rca_tpu.serve.federation import federation_selftest

    out = federation_selftest(
        workers=2, n_requests=12, seed=0, services=(24, 48),
    )
    assert out["ok"], out
    assert out["parity_ok"] and out["parity_checked"] >= 8
    assert out["double_completions"] == 0
    assert out["by_status"].get("shed", 0) >= out["expected_shed_min"]


def test_federation_selftest_kill_worker_gate():
    """The ISSUE 15 acceptance gate, scaled to CI: worker processes
    under wire load, one SIGKILLed mid-wave — every request terminal,
    survivors bit-identical to the single-process engine,
    double_completions == 0."""
    from rca_tpu.serve.federation import federation_selftest

    out = federation_selftest(
        workers=3, n_requests=18, seed=1, kill_worker=True,
        services=(24, 48), submitters=4,
    )
    assert out["ok"], out
    assert out["double_completions"] == 0
    assert "process_kill" in out["fault_classes_observed"]
    assert out["parity_ok"]
    assert out["all_resolved"]
    assert out.get("recovery_ms") is not None


def test_fed_fault_classes_vocabulary():
    assert set(FED_FAULT_CLASSES) == {
        "process_kill", "worker_hang", "coordinator_partition",
    }
