"""Trust logic of the native .so compile cache (advisor finding, round 2).

The cache must never load — or write through — anything another local
user could have planted: a world-shared /tmp dir, a pre-seeded
hash-predictable .so, a symlinked fallback path.  Self-owned artifacts
from a looser-umask era are REPAIRED (chmod/rebuild), never a permanent
silent fallback to the slow Python paths.

Uses a trivial one-function source so each cold compile costs
milliseconds — the logscan/sanitize codegen itself is covered by
tests/test_native.py.
"""

import os

import pytest

from rca_tpu import native

TINY_SRC = "extern \"C\" int rca_cache_probe(void) { return 7; }\n"


@pytest.fixture()
def tiny_source(tmp_path):
    src = tmp_path / "probe.cpp"
    src.write_text(TINY_SRC)
    return src


def _compile(src):
    return native._compile_cached(src, "probe", ["-std=c++17"])


def test_loose_self_owned_default_dir_is_repaired(tmp_path, monkeypatch,
                                                  tiny_source):
    # group/other-writable DEFAULT cache dir we own -> chmod 0700 closes
    # the write window before any compile; never a permanent silent
    # fallback.  (Only the default dir: the tool created it, so it is not
    # a deliberately-shared location.)
    loose = tmp_path / "loose"
    loose.mkdir()
    os.chmod(loose, 0o777)
    monkeypatch.delenv("RCA_NATIVE_CACHE", raising=False)
    monkeypatch.setattr(native, "_default_cache_dir", lambda: loose)
    out = _compile(tiny_source)
    if out is None:
        pytest.skip("no toolchain")
    assert (os.stat(loose).st_mode & 0o777) == 0o700


def test_loose_env_configured_dir_is_rejected_not_mutated(
        tmp_path, monkeypatch, tiny_source):
    # an env-configured loose dir may be a deliberately group-shared team
    # cache (e.g. mode 2775): warn + reject, never chmod it out from
    # under its other users
    shared = tmp_path / "shared"
    shared.mkdir()
    os.chmod(shared, 0o775)
    monkeypatch.setenv("RCA_NATIVE_CACHE", str(shared))
    with pytest.warns(RuntimeWarning, match="not exclusively owned"):
        assert _compile(tiny_source) is None
    assert (os.stat(shared).st_mode & 0o777) == 0o775  # untouched


def test_explicit_symlink_cache_is_followed(tmp_path, monkeypatch,
                                            tiny_source):
    # a user-configured symlink to a private dir is legitimate (resolved
    # before the ownership checks, not lstat'ed)
    target = tmp_path / "real-cache"
    link = tmp_path / "link-cache"
    link.symlink_to(target)
    monkeypatch.setenv("RCA_NATIVE_CACHE", str(link))
    out = _compile(tiny_source)
    if out is None:
        pytest.skip("no toolchain")
    assert str(out).startswith(str(target))


def test_private_dir_and_stale_artifact_repair(tmp_path, monkeypatch,
                                               tiny_source):
    tight = tmp_path / "tight"
    monkeypatch.setenv("RCA_NATIVE_CACHE", str(tight))
    out = _compile(tiny_source)
    if out is None:
        pytest.skip("no toolchain")
    st = os.stat(tight)
    assert st.st_uid == os.getuid()
    assert (st.st_mode & 0o022) == 0
    assert (os.stat(out).st_mode & 0o777) == 0o600
    # a loose artifact inside a dir we own exclusively is our own stale
    # file (nobody else could have written it) — repaired by rebuild
    os.chmod(out, 0o666)
    out2 = _compile(tiny_source)
    assert out2 is not None
    assert (os.stat(out2).st_mode & 0o777) == 0o600
    # a foreign-looking .so at the final name is unlinked and rebuilt,
    # and a symlink there never gets written THROUGH (unlink removes the
    # link, not its target)
    victim = tmp_path / "victim.txt"
    victim.write_text("precious")
    out2.unlink()
    out2.symlink_to(victim)
    out3 = _compile(tiny_source)
    assert out3 is not None and not out3.is_symlink()
    assert victim.read_text() == "precious"


def test_default_fallback_never_follows_preseeded_symlink(
        tmp_path, monkeypatch, tiny_source):
    # the /tmp fallback name is predictable and /tmp is world-writable: a
    # pre-seeded symlink must be rejected outright, not chmod'd/written to
    victim_dir = tmp_path / "victim-dir"
    victim_dir.mkdir()
    os.chmod(victim_dir, 0o770)  # deliberately group-shared
    fake_default = tmp_path / "preseeded-link"
    fake_default.symlink_to(victim_dir)
    monkeypatch.delenv("RCA_NATIVE_CACHE", raising=False)
    monkeypatch.setattr(native, "_default_cache_dir", lambda: fake_default)
    assert _compile(tiny_source) is None
    assert (os.stat(victim_dir).st_mode & 0o777) == 0o770  # untouched


def test_default_cache_dir_is_user_scoped():
    d = native._default_cache_dir()
    if str(os.getuid()) in d.name:  # tempdir fallback (HOME-less env)
        return
    assert str(d).startswith(str(native.Path.home()))
