"""gravelock (rca_tpu/analysis/concurrency, ANALYSIS.md): the static
race/deadlock analyzer finds what it must and nothing else, the rsan
runtime shim records real executions, the cross-check catches an
inverted acquire order BOTH ways, the serve scheduler survives a seeded
8-thread barrage with the sanitizer on, and `rca lint --changed` agrees
with a full run on the touched files."""

from __future__ import annotations

import json
import os
import textwrap
import threading

import numpy as np
import pytest

from rca_tpu.analysis import run_lint
from rca_tpu.analysis.concurrency import model_for, rsan
from rca_tpu.analysis.concurrency.crosscheck import (
    order_contradictions,
    queue_metrics_stress,
    run_rsan_crosscheck,
)
from rca_tpu.analysis.concurrency.lockorder import analyze_lock_order
from rca_tpu.analysis.concurrency.races import analyze_races
from rca_tpu.analysis.core import changed_files, repo_root
from rca_tpu.util.threads import make_lock, make_thread, spawn

ROOT = repo_root()


@pytest.fixture
def sanitized():
    """rsan on for the test body, restored (and drained) afterwards."""
    was = rsan.enabled()
    rsan.enable()
    rsan.RSAN.reset()
    try:
        yield rsan.RSAN
    finally:
        rsan.RSAN.reset()
        if not was:
            rsan.disable()


def _fake_repo(tmp_path, *entries):
    for rel, src in entries:
        full = tmp_path / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(textwrap.dedent(src))
    return str(tmp_path)


INVERTED = ("rca_tpu/serve/inverted.py", """\
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            self._inner_b()

    def _inner_b(self):
        with self._b:
            pass

    def backward(self):
        with self._b:
            with self._a:
                pass
""")


# ---------------------------------------------------------------------------
# static model
# ---------------------------------------------------------------------------

def test_repo_thread_roots_discovered():
    """Root discovery sees every way this repo starts a thread: the
    serve worker (make_thread target), the watch pumps (Thread
    subclass, multi-instance), and the selftest submitters (closure
    spawned in a comprehension, multi-instance)."""
    m = model_for(ROOT)
    roots = {r.root_id: r for r in m.roots}
    assert "rca-serve" in roots
    assert "_Pump" in roots and roots["_Pump"].multi
    assert "submitter" in roots and roots["submitter"].multi


def test_repo_statically_clean():
    """After this PR's fixes (Retry counter lock, watch-pump token
    counter) the package carries no race or deadlock findings — the
    empty-baseline acceptance criterion for the new rules."""
    m = model_for(ROOT)
    assert analyze_races(m) == []
    assert analyze_lock_order(m) == []


def test_static_catches_unguarded_multiroot_write(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/serve/w.py", """\
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._done = 0
            self._thread = None

        def start(self):
            self._thread = threading.Thread(
                target=self._run, name="w", daemon=True
            )
            self._thread.start()

        def _run(self):
            self._done += 1

        def bump(self):
            with self._lock:
                self._done += 1
    """))
    result = run_lint(root=root, rules=["race-guard"], use_baseline=False)
    assert len(result.findings) == 1
    f = result.findings[0]
    assert "dominant guard is `Worker._lock`" in f.message
    assert f.func == "_run"


def test_static_catches_shared_instance_across_spawned_copies(tmp_path):
    """The Retry-counter shape: one object handed to N copies of the
    same thread root, mutated with no lock anywhere."""
    root = _fake_repo(tmp_path, ("rca_tpu/serve/b.py", """\
    import threading

    class Budget:
        def __init__(self):
            self.spent = 0

        def charge(self):
            self.spent += 1

    class Owner:
        def __init__(self):
            self.budget = Budget()
            self.threads = [
                threading.Thread(
                    target=self.work, name="worker", daemon=True
                )
                for _ in range(2)
            ]

        def work(self):
            self.budget.charge()
    """))
    result = run_lint(root=root, rules=["race-guard"], use_baseline=False)
    assert len(result.findings) == 1
    assert "no common lock" in result.findings[0].message


def test_static_distinct_instances_do_not_pair(tmp_path):
    """Per-instance state consistently guarded per owner must NOT flag,
    even when one owner's accesses ride a worker thread and the other's
    ride main — the receiver-context approximation at work (this is the
    PhaseStats shape that a naive per-class lockset would flag)."""
    root = _fake_repo(tmp_path, ("rca_tpu/serve/p.py", """\
    import threading

    class Stats:
        def __init__(self):
            self.samples = []

        def record(self, x):
            self.samples.append(x)

    class Guarded:
        def __init__(self):
            self._lock = threading.Lock()
            self.stats = Stats()
            self._thread = None

        def start(self):
            self._thread = threading.Thread(
                target=self._run, name="g", daemon=True
            )
            self._thread.start()

        def _run(self):
            with self._lock:
                self.stats.record(1)

    class Unshared:
        def __init__(self):
            self.stats = Stats()

        def tick(self):
            self.stats.record(2)
    """))
    result = run_lint(root=root, rules=["race-guard"], use_baseline=False)
    assert result.clean, result.findings


def test_static_lock_order_cycle_reports_chains(tmp_path):
    root = _fake_repo(tmp_path, INVERTED)
    result = run_lint(root=root, rules=["lock-order"], use_baseline=False)
    assert len(result.findings) == 1
    msg = result.findings[0].message
    assert "Pair._a -> Pair._b" in msg and "Pair._b -> Pair._a" in msg
    # the cross-call chain is named: where the outer was held and where
    # the nested acquire happened
    assert "Pair.forward" in msg and "Pair._inner_b" in msg


def test_thread_discipline_rule(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/x.py", """\
    import threading
    from threading import Lock

    def bad(fn):
        a = threading.Lock()
        b = Lock()
        t = threading.Thread(target=fn)
        return a, b, t
    """), ("rca_tpu/y.py", """\
    from rca_tpu.util.threads import make_lock, spawn

    def good(fn):
        a = make_lock("y.a")
        return a, spawn(fn, name="worker")
    """))
    result = run_lint(root=root, rules=["thread-discipline"],
                      use_baseline=False)
    assert len(result.findings) == 3
    assert all(f.path == "rca_tpu/x.py" for f in result.findings)


# ---------------------------------------------------------------------------
# rsan runtime shim
# ---------------------------------------------------------------------------

def test_constructors_zero_cost_when_off():
    was = rsan.enabled()
    rsan.disable()
    try:
        lock = make_lock("t.lock")
        assert isinstance(lock, type(threading.Lock()))
    finally:
        if was:
            rsan.enable()


def test_env_seeds_rsan(monkeypatch):
    monkeypatch.setenv("RCA_RSAN", "1")
    monkeypatch.setattr(rsan, "_ENABLED", None)
    assert rsan.enabled()
    lock = make_lock("t.env")
    assert isinstance(lock, rsan.SanitizedLock)
    monkeypatch.setattr(rsan, "_ENABLED", False)


def test_rsan_records_order_edges_and_threads(sanitized):
    a = make_lock("T._a")
    b = make_lock("T._b")

    def nested():
        with a:
            with b:
                pass

    t = spawn(nested, name="edge-maker")
    t.join(10.0)
    nested()
    edges = sanitized.order_edges()
    assert ("T._a", "T._b") in edges
    rec = edges[("T._a", "T._b")]
    assert rec["count"] == 2
    assert set(rec["threads"]) >= {"edge-maker"}
    assert sanitized.lock_threads()["T._a"]


def test_rsan_observes_unguarded_write_pair(sanitized):
    lock = make_lock("T._lock")

    def guarded():
        with lock:
            rsan.note_access("Obj", "guarded")

    def unguarded():
        rsan.note_access("Obj", "naked")

    ts = [spawn(guarded, name=f"g{i}") for i in range(2)]
    ts += [spawn(unguarded, name=f"u{i}") for i in range(2)]
    for t in ts:
        t.join(10.0)
    races = sanitized.races_observed()
    keys = {(r["owner"], r["attr"]) for r in races}
    assert ("Obj", "naked") in keys       # disjoint (empty) locksets
    assert ("Obj", "guarded") not in keys  # common lock -> no pair


def test_sanitized_condition_wait_rebalances_held_stack(sanitized):
    from rca_tpu.util.threads import make_condition

    cond = make_condition("T._cond")
    outcome = {}

    def waiter():
        with cond:
            cond.wait(0.05)
            outcome["held_after_wait"] = rsan.held_locks()
        outcome["held_after_exit"] = rsan.held_locks()

    t = spawn(waiter, name="waiter")
    t.join(10.0)
    assert outcome["held_after_wait"] == ("T._cond",)
    assert outcome["held_after_exit"] == ()


# ---------------------------------------------------------------------------
# the cross-check: static <-> runtime
# ---------------------------------------------------------------------------

def test_inverted_order_caught_statically_and_dynamically(
        tmp_path, sanitized):
    """THE acceptance scenario: the same inversion is a static lock-order
    finding AND an rsan order contradiction when executed."""
    # static leg: the fixture repo carries the cycle
    root = _fake_repo(tmp_path, INVERTED)
    static = run_lint(root=root, rules=["lock-order"], use_baseline=False)
    assert len(static.findings) == 1

    # dynamic leg: actually run both orders (sequentially — the point is
    # the record, not a live deadlock) and diff against the static graph
    a = make_lock("Pair._a")
    b = make_lock("Pair._b")

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    t1 = spawn(forward, name="fwd")
    t1.join(10.0)
    t2 = spawn(backward, name="bwd")
    t2.join(10.0)

    model = model_for(root)
    contradictions = order_contradictions(
        model.static_order_edges(), sanitized.order_edges()
    )
    edges = {tuple(c["edge"]) for c in contradictions}
    # both observed directions close a cycle in the combined graph
    assert ("Pair._a", "Pair._b") in edges
    assert ("Pair._b", "Pair._a") in edges


def test_order_contradiction_against_static_graph_only():
    """An inversion of an edge only the STATIC graph knows is still a
    contradiction — the runtime saw half a deadlock."""
    observed = {
        ("B", "A"): {"count": 1, "threads": ["t"], "chain": ["B", "A"]},
    }
    out = order_contradictions({("A", "B")}, observed)
    assert [c["edge"] for c in out] == [["B", "A"]]
    assert order_contradictions({("A", "B")}, {
        ("A", "B"): {"count": 1, "threads": ["t"], "chain": ["A", "B"]},
    }) == []


# ---------------------------------------------------------------------------
# tier-1 concurrency stress (RCA_RSAN=1)
# ---------------------------------------------------------------------------

def test_queue_metrics_stress_under_rsan(sanitized):
    """Satellite: seeded 8-thread barrage over RequestQueue
    submit/pop/shed/shutdown-drain + ServeMetrics counters, with every
    lock sanitized.  Exact totals — a lost update fails loudly."""
    out = queue_metrics_stress(seed=11, threads=8)
    assert out["ok"], out
    assert out["submitted_counted"] == out["requests"]
    assert out["completed_counted"] == out["requests"]
    assert out["queue_leftover"] == 0
    # coverage: the queue's condition and the metrics lock were really
    # contended across threads
    lt = sanitized.lock_threads()
    assert len(lt["RequestQueue._cond"]) >= 2
    assert len(lt["ServeMetrics._lock"]) >= 2
    assert sanitized.races_observed() == []


def test_rsan_crosscheck_with_chaos_soak():
    """Acceptance: the full cross-check — stress + a 40-tick seeded
    chaos soak — runs clean against the repo's static model."""
    out = run_rsan_crosscheck(seed=7, soak_ticks=40)
    assert out["ok"], json.dumps(
        {k: out[k] for k in ("contradictions", "races_observed",
                             "stress", "soak")}, default=str)
    assert out["soak"]["ticks"] == 40
    assert out["soak"]["uncaught_exceptions"] == 0
    assert out["contradictions"] == []
    assert out["races_observed"] == []
    assert len(out["multi_thread_locks"]) >= 2
    assert not rsan.enabled()  # the check restores the off state


# ---------------------------------------------------------------------------
# regression: the races this analyzer surfaced (and this PR fixed)
# ---------------------------------------------------------------------------

def test_retry_counter_is_thread_safe():
    """Pre-fix, `Retry.retries_spent += 1` was an unguarded RMW on an
    object the watch-pump set shares across both pump threads; under a
    barrage the counter lost updates."""
    from rca_tpu.resilience.policy import Retry

    retry = Retry(attempts=2, sleep=lambda s: None, seed=0)
    n_threads, per_thread = 8, 400

    def worker():
        for _ in range(per_thread):
            retry.sleep_for(1)

    threads = [
        make_thread(worker, name=f"retry-{i}", daemon=True)
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert retry.retries_spent == n_threads * per_thread


def test_watch_pump_tokens_unique_across_sets():
    """Pre-fix, the consumer-token counter was a CLASS attribute guarded
    by each instance's own lock — two namespaces' pump sets could mint
    the same token.  Tokens must be process-unique."""
    from rca_tpu.cluster.watch_pump import WatchPumpSet

    sets = [WatchPumpSet(core_api=None, namespace=f"ns{i}")
            for i in range(4)]
    tokens: list = []
    lock = threading.Lock()

    def register_many(ps):
        got = [ps.register() for _ in range(50)]
        with lock:
            tokens.extend(got)

    threads = [
        make_thread(register_many, name=f"reg-{i}", daemon=True,
                    args=(ps,))
        for i, ps in enumerate(sets)
        for _ in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert len(tokens) == len(set(tokens)) == 4 * 2 * 50


# ---------------------------------------------------------------------------
# incremental lint (`rca lint --changed`)
# ---------------------------------------------------------------------------

def test_changed_parity(tmp_path):
    """--changed on the touched files reports exactly what a full run
    reports for those files (the interprocedural model is whole-package
    either way)."""
    from rca_tpu.analysis.__main__ import main

    root = _fake_repo(
        tmp_path,
        ("rca_tpu/clean.py", "X = 1\n"),
        ("rca_tpu/serve/w.py", """\
        import os

        def f():
            return os.environ.get("RCA_X")
        """),
    )
    # first full run seeds the fingerprint index (findings exist -> 1)
    assert main(["--root", root, "--no-baseline"]) == 1
    assert changed_files(root) == []

    # touch one file: only it is re-linted, findings parity holds
    (tmp_path / "rca_tpu/clean.py").write_text(
        "import threading\nL = threading.Lock()\n"
    )
    assert changed_files(root) == ["rca_tpu/clean.py"]
    full = run_lint(root=root, use_baseline=False)
    full_for_file = [
        f.to_dict() for f in full.findings
        if f.path == "rca_tpu/clean.py"
    ]
    subset = run_lint(root=root, paths=["rca_tpu/clean.py"],
                      use_baseline=False)
    assert [f.to_dict() for f in subset.findings] == full_for_file
    assert len(full_for_file) == 1  # the raw-lock thread-discipline hit

    # the CLI --changed path consumes the index and exits on findings
    assert main(["--root", root, "--changed", "--no-baseline"]) == 1
    assert changed_files(root) == []
    assert main(["--root", root, "--changed", "--no-baseline"]) == 0


def test_changed_rejects_explicit_paths(tmp_path):
    from rca_tpu.analysis.__main__ import main

    root = _fake_repo(tmp_path, ("rca_tpu/clean.py", "X = 1\n"))
    assert main(["--root", root, "--changed", "rca_tpu/clean.py"]) == 2


def test_index_survives_missing_git(tmp_path):
    root = _fake_repo(tmp_path, ("rca_tpu/a.py", "A = 1\n"))
    # no git repo at tmp_path: the fingerprint index alone drives it
    assert changed_files(root) == ["rca_tpu/a.py"]
    from rca_tpu.analysis.core import update_index

    update_index(root, ["rca_tpu/a.py"])
    assert changed_files(root) == []
    (tmp_path / "rca_tpu/a.py").write_text("A = 2\n")
    assert changed_files(root) == ["rca_tpu/a.py"]


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_rsan_json_shape(tmp_path, capsys):
    from rca_tpu.analysis.__main__ import main

    root = _fake_repo(tmp_path, ("rca_tpu/clean.py", "X = 1\n"))
    rc = main(["--root", root, "--no-baseline", "--json", "--rsan"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["clean"] is True
    assert out["rsan"]["ok"] is True
    assert out["rsan"]["stress"]["ok"] is True
    assert out["rsan"]["contradictions"] == []
    assert not rsan.enabled()
