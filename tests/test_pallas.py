"""Pallas fused noisy-OR kernel: interpret-mode correctness (CPU CI) and
live-backend agreement when Mosaic is available."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from rca_tpu.engine.pallas_kernels import (  # noqa: E402
    BLOCK_S,
    noisy_or_pair_pallas,
    noisy_or_pair_xla,
    pallas_supported,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(1)
    S, C = 2 * BLOCK_S, 12
    f = rng.random((S, C)).astype(np.float32)
    return (
        jnp.asarray(f),
        jnp.asarray(np.ascontiguousarray(f.T)),
        jnp.asarray(rng.random(C).astype(np.float32)),
        jnp.asarray(rng.random(C).astype(np.float32)),
    )


def test_interpret_matches_xla(data):
    f, ft, aw, hw = data
    a_ref, h_ref = noisy_or_pair_xla(f, aw, hw)
    a, h = noisy_or_pair_pallas(ft, aw, hw, interpret=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), atol=1e-6)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref), atol=1e-6)


def test_live_backend_if_supported(data):
    if not pallas_supported():
        pytest.skip("pallas not lowerable on this backend")
    f, ft, aw, hw = data
    a_ref, h_ref = noisy_or_pair_xla(f, aw, hw)
    a, h = noisy_or_pair_pallas(ft, aw, hw)
    np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), atol=1e-6)
    np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref), atol=1e-6)


def test_env_flag_disables(monkeypatch):
    import rca_tpu.engine.pallas_kernels as pk

    monkeypatch.setenv("RCA_PALLAS", "0")
    assert pk.pallas_supported() is False


def test_engine_routing_is_opt_in(monkeypatch):
    """The kernel measures as a wash vs XLA on real TPU, so the engine only
    routes through it under RCA_PALLAS=1 (capability stays probed/tested)."""
    import rca_tpu.engine.pallas_kernels as pk

    monkeypatch.setenv("RCA_PALLAS", "auto")
    assert pk.pallas_enabled() is False
    monkeypatch.setenv("RCA_PALLAS", "1")
    monkeypatch.setattr(pk, "_SUPPORTED", True)
    assert pk.pallas_enabled() is True
