"""planetcap (ISSUE 17): live columnar ingestion + multi-cluster
federated capture.

Four claims under test:

1. The LIVE columnar adapter (``LiveColumnarFeed``, the watch-pump path
   the real ``K8sApiClient`` uses) is BIT-identical to the dict path
   through ``extract_features`` under seeded churn — the same property
   the mock's native columnar master is held to — and a cursor expiry
   (the 410 analogue: the watch journal trimmed past the cursor) forces
   a full rebuild with NO silent gap: changes made inside the expiry
   window appear in the post-expiry payload.
2. The merged multi-cluster world (``ClusterSet`` /
   ``MergedClusterClient``) rejects identity collisions loudly, keeps
   digests stable against member insertion order, and holds the same
   columnar-vs-dict bit parity across cross-cluster churn.
3. Multi-cluster recordings replay bit-identically at pipeline depths
   1 AND 2 (the committed ``multicluster-3x20svc-seed17.rcz`` fixture).
4. The ingest control plane applies each capture tick AT MOST once:
   the coordinator's cluster table drops wrong-owner / stale-epoch /
   replayed-seq stats, rendezvous assignment names exactly one owner
   per cluster, and the runner resumes the dead owner's tick count.
"""

from __future__ import annotations

import json
import math
import os
import time
from types import SimpleNamespace

import numpy as np
import pytest

from rca_tpu.cluster.clusterset import ClusterSet
from rca_tpu.cluster.columnar import ColumnarClientState
from rca_tpu.cluster.generator import synthetic_cascade_world
from rca_tpu.cluster.live_columnar import LiveColumnarFeed
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot
from rca_tpu.cluster.world import make_pod
from rca_tpu.features.extract import extract_features

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class LiveShim:
    """A mock client whose ``get_columnar`` is the LIVE watch-pump
    adapter instead of the mock's native columnar master — captures
    through this pay what a real apiserver-backed ingest pays."""

    def __init__(self, inner, ns):
        self._inner = inner
        self.feed = LiveColumnarFeed(inner, ns)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def get_columnar(self, namespace, cursor=None):
        return self.feed.payload(cursor)

    def close(self):
        self.feed.close()


def _fs_equal(a, b) -> bool:
    return (
        a.pod_names == b.pod_names
        and a.service_names == b.service_names
        and a.node_names == b.node_names
        and a.pod_features.tobytes() == b.pod_features.tobytes()
        and a.service_features.tobytes() == b.service_features.tobytes()
        and a.node_features.tobytes() == b.node_features.tobytes()
        and a.pod_service.tobytes() == b.pod_service.tobytes()
        and a.pod_node.tobytes() == b.pod_node.tobytes()
        and a.memb_pod.tobytes() == b.memb_pod.tobytes()
        and a.memb_svc.tobytes() == b.memb_svc.tobytes()
    )


def _expire_watch(world) -> None:
    """The 410 analogue for the mock watch feed: trim the journal past
    every registered cursor, so the next drain reports ``expired``."""
    world.journal.clear()
    world.journal_floor = world.journal_seq + 2
    world.journal_seq += 1


def _churn(world, ns, rng, step):
    """One seeded mutation drawn from the property domain: metric
    touch, pod update, pod delete, pod add, NaN metric."""
    pods = world.pods.get(ns, [])
    op = int(rng.integers(0, 5))
    if op == 0 and pods:
        name = pods[int(rng.integers(0, len(pods)))]["metadata"]["name"]
        world.touch("pod_metrics", ns, name)
    elif op == 1 and pods:
        pod = pods[int(rng.integers(0, len(pods)))]
        pod["status"]["phase"] = (
            "Failed" if pod["status"]["phase"] == "Running" else "Running"
        )
        world.touch("pod", ns, pod["metadata"]["name"])
    elif op == 2 and len(pods) > 2:
        pod = pods[int(rng.integers(0, len(pods)))]
        name = pod["metadata"]["name"]
        pods.remove(pod)
        world.touch("pod", ns, name)
    elif op == 3:
        node = world.nodes[0]["metadata"]["name"]
        name = f"clone-{step}"
        world.add("pods", ns, make_pod(name, ns, app=f"clone{step}",
                                       node_name=node))
        world.touch("pod", ns, name)
    else:
        recs = (world.pod_metrics.get(ns) or {}).get("pods") or {}
        if recs:
            names = sorted(recs)
            name = names[int(rng.integers(0, len(names)))]
            # REPLACE the record (a real apiserver returns fresh parsed
            # objects per call); an in-place mutation of the mock's
            # aliased rec would be invisible to any snapshot differ
            rec = recs[name]
            recs[name] = {
                **rec,
                "cpu": {**rec["cpu"], "usage_percentage": float("nan")},
            }
            world.touch("pod_metrics", ns, name)


# -- 1. the live adapter --------------------------------------------------


def test_live_adapter_parity_property():
    """Seeded churn property: capture through the LIVE adapter ==
    capture through the dict path, bitwise, at every step — exactly the
    gate the mock's native columnar master passes."""
    ns = "live"
    world = synthetic_cascade_world(14, n_roots=1, seed=5, namespace=ns,
                                    pods_per_service=2)
    client = LiveShim(MockClusterClient(world), ns)
    state = ColumnarClientState()
    rng = np.random.default_rng(17)
    snap = ClusterSnapshot.capture(client, ns, columnar_state=state)
    for step in range(24):
        _churn(world, ns, rng, step)
        snap = ClusterSnapshot.capture(
            client, ns, columnar_state=state, traces_from=snap.traces,
        )
        fs_live = extract_features(snap)
        snap_d = ClusterSnapshot.capture(
            client._inner, ns, columnar=False, traces_from=snap.traces,
        )
        fs_dict = extract_features(snap_d)
        assert _fs_equal(fs_live, fs_dict), (
            f"live-vs-dict divergence at churn step {step}"
        )
    client.close()


def test_cursor_expiry_rebuilds_without_gap():
    """The 410 leg: changes made while the watch journal was trimmed
    past the feed's cursor must appear in the post-expiry payload —
    expiry means FULL REBUILD, never a silent gap."""
    ns = "gap"
    world = synthetic_cascade_world(10, n_roots=1, seed=3, namespace=ns)
    client = LiveShim(MockClusterClient(world), ns)
    state = ColumnarClientState()
    snap = ClusterSnapshot.capture(client, ns, columnar_state=state)
    resyncs_before = client.feed.resyncs

    # mutate INSIDE the expiry window: a pod flips to Failed and one is
    # deleted, then the journal is trimmed past the feed's cursor
    victim = world.pods[ns][0]
    victim["status"]["phase"] = "Failed"
    world.touch("pod", ns, victim["metadata"]["name"])
    gone = world.pods[ns][1]
    world.pods[ns].remove(gone)
    world.touch("pod", ns, gone["metadata"]["name"])
    _expire_watch(world)

    snap = ClusterSnapshot.capture(
        client, ns, columnar_state=state, traces_from=snap.traces,
    )
    fs_live = extract_features(snap)
    snap_d = ClusterSnapshot.capture(
        client._inner, ns, columnar=False, traces_from=snap.traces,
    )
    fs_dict = extract_features(snap_d)
    assert client.feed.resyncs == resyncs_before + 1, (
        "expiry must force exactly one full re-list reconcile"
    )
    assert gone["metadata"]["name"] not in fs_live.pod_names
    assert _fs_equal(fs_live, fs_dict), (
        "post-expiry capture diverged from the dict path — the rebuild "
        "left a gap"
    )
    client.close()


def test_expired_external_cursor_serves_full_dump():
    """A consumer holding a pre-expiry cursor gets a FULL payload after
    the feed rebuilt — not an empty diff (the silent-gap failure)."""
    ns = "cur"
    world = synthetic_cascade_world(8, n_roots=1, seed=2, namespace=ns)
    feed = LiveColumnarFeed(MockClusterClient(world), ns)
    first = feed.payload(None)
    assert first.get("supported") and first.get("full")
    cursor = first["cursor"]
    world.touch("pod_metrics", ns,
                world.pods[ns][0]["metadata"]["name"])
    _expire_watch(world)
    p = feed.payload(cursor)
    assert p.get("supported")
    assert p.get("full"), (
        "stale cursor after expiry must be answered with a full dump"
    )
    feed.close()


# -- 2. the merged multi-cluster world ------------------------------------


def _three_cluster_set(seed=17, services=6):
    worlds = {
        f"c{j}": synthetic_cascade_world(
            services, n_roots=1, seed=seed + j, namespace="synthetic",
        )
        for j in range(3)
    }
    cset = ClusterSet({
        cid: MockClusterClient(w) for cid, w in worlds.items()
    })
    return worlds, cset


def test_namespace_collision_rejected():
    world = synthetic_cascade_world(4, n_roots=1, seed=0,
                                    namespace="synthetic")
    with pytest.raises(ValueError, match="cluster id"):
        ClusterSet({"a/b": MockClusterClient(world)})
    with pytest.raises(ValueError, match="cluster id"):
        ClusterSet({"": MockClusterClient(world)})
    with pytest.raises(ValueError, match="cluster id"):
        ClusterSet({" c0": MockClusterClient(world)})

    # a member NAMESPACE carrying the separator would alias another
    # cluster's prefixed path: rejected at every merged surface
    bad = synthetic_cascade_world(4, n_roots=1, seed=0,
                                  namespace="evil/synthetic")
    cset = ClusterSet({"c0": MockClusterClient(bad)})
    with pytest.raises(ValueError, match="alias"):
        cset.namespaces()
    with pytest.raises(ValueError, match="alias"):
        cset.merged_client().get_namespaces()


def test_digest_stability_and_sensitivity():
    worlds, cset = _three_cluster_set()
    # member INSERTION order must not move any digest
    reordered = ClusterSet({
        cid: cset.members[cid] for cid in ("c2", "c0", "c1")
    })
    assert cset.graph_digest() == reordered.graph_digest()
    for cid in cset.ids:
        assert cset.cluster_digest(cid) == reordered.cluster_digest(cid)

    # pod churn (metrics, status) must not move the TOPOLOGY digest
    before = cset.cluster_digest("c0")
    worlds["c0"].touch(
        "pod_metrics", "synthetic",
        worlds["c0"].pods["synthetic"][0]["metadata"]["name"],
    )
    assert cset.cluster_digest("c0") == before

    # a topology change (new service) MUST move that cluster's digest
    # and the graph digest, and leave the siblings' digests alone
    sib = cset.cluster_digest("c1")
    graph = cset.graph_digest()
    from rca_tpu.cluster.world import make_service

    worlds["c0"].add("services", "synthetic",
                     make_service("svc-new", "synthetic", {"app": "new"}))
    worlds["c0"].touch("service", "synthetic", "svc-new")
    assert cset.cluster_digest("c0") != before
    assert cset.graph_digest() != graph
    assert cset.cluster_digest("c1") == sib


def test_merged_columnar_parity_under_cross_cluster_churn():
    """The merged view's live columnar feed vs the merged dict path,
    bitwise, through cross-cluster churn — including a pod ADD (the
    mid-list insert that forces the reorder+rebuild path) and deletes."""
    worlds, cset = _three_cluster_set()
    merged = cset.merged_client()
    ns = "synthetic"
    state = ColumnarClientState()
    snap = ClusterSnapshot.capture(merged, ns, columnar_state=state)
    rng = np.random.default_rng(7)
    for step in range(12):
        cid = f"c{step % 3}"
        _churn(worlds[cid], ns, rng, step)
        snap = ClusterSnapshot.capture(
            merged, ns, columnar_state=state, traces_from=snap.traces,
        )
        fs_col = extract_features(snap)
        snap_d = ClusterSnapshot.capture(
            merged, ns, columnar=False, traces_from=snap.traces,
        )
        fs_dict = extract_features(snap_d)
        assert _fs_equal(fs_col, fs_dict), (
            f"merged live-vs-dict divergence at step {step} ({cid})"
        )
    # every pod name is cluster-prefixed and service edges stay local
    assert all("/" in n for n in fs_dict.pod_names)
    deps = merged.get_service_dependencies(ns)
    for src, dsts in deps.items():
        scid = src.split("/", 1)[0]
        assert all(d.split("/", 1)[0] == scid for d in dsts), (
            f"cross-cluster edge leaked from {src}"
        )
    merged.close()


# -- 3. multi-cluster replay ----------------------------------------------


FIXTURE = os.path.join(
    REPO_ROOT, "tests", "corpus", "multicluster-3x20svc-seed17.rcz"
)


@pytest.mark.parametrize("depth", [1, 2])
def test_multicluster_fixture_replays_at_depth(depth):
    """The committed merged-capture fixture holds bit parity when
    replayed at pipeline depth 1 AND depth 2 — pipelining must not
    move a bit on multi-cluster frames any more than single-cluster."""
    from rca_tpu.replay import replay_stream

    report = replay_stream(FIXTURE, pipeline_depth=depth)
    assert report["parity_ok"], {
        k: report.get(k)
        for k in ("first_divergent_tick", "mismatched_ticks")
    }
    assert report["pipeline_depth_replayed"] == depth
    assert report["ticks_replayed"] == report["ticks_recorded"]


# -- 4. the ingest control plane ------------------------------------------


class _FakeConn:
    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)


def _ingest_handle(plane, wid):
    from rca_tpu.serve.federation import _WorkerHandle

    w = _WorkerHandle(wid)
    w.role = "ingest"
    w.live = True
    w.conn = _FakeConn()
    plane.workers[wid] = w
    plane.ingest_ring.add(wid)
    return w


def test_ingest_rebalance_single_owner_moves_and_reclaims():
    from rca_tpu.serve.federation import FederationPlane

    plane = FederationPlane(workers=0, spawn_workers=False)
    w1, w2 = _ingest_handle(plane, 1), _ingest_handle(plane, 2)
    plane.register_clusters({
        f"k{i}": {"digest": f"d{i}"} for i in range(6)
    })
    owners = {cid: e["owner"] for cid, e in plane.clusters.items()}
    assert set(owners.values()) <= {1, 2}
    assert all(e["epoch"] == 1 for e in plane.clusters.values())
    assigns = [m for m in w1.conn.sent + w2.conn.sent
               if m["t"] == "ingest_assign"]
    assert len(assigns) == 6 and all(
        m["resume_seq"] == 0 for m in assigns
    )

    # the owner dies: every orphan moves to the one survivor with a
    # fresh epoch and the last applied seq as resume point; the corpse
    # gets no unassign frame
    mine = sorted(c for c, o in owners.items() if o == 1)
    assert mine, "rendezvous should spread 6 clusters over 2 workers"
    for cid in mine:
        plane.clusters[cid]["last_seq"] = 41
    plane.ingest_ring.remove(1)
    w1.live = False
    dead_frames = len(w1.conn.sent)
    plane._ingest_rebalance()
    for cid in mine:
        ent = plane.clusters[cid]
        assert ent["owner"] == 2 and ent["epoch"] == 2
    assert len(w1.conn.sent) == dead_frames
    resumed = [m for m in w2.conn.sent
               if m["t"] == "ingest_assign" and m["cluster"] in mine]
    assert all(m["resume_seq"] == 41 for m in resumed)

    # rejoin: HRW stickiness hands back exactly the clusters it owned
    w1.live = True
    plane.ingest_ring.add(1)
    plane._ingest_rebalance()
    now_mine = sorted(
        c for c, e in plane.clusters.items() if e["owner"] == 1
    )
    assert now_mine == mine


def test_ingest_stat_exactly_once_arbiter():
    from rca_tpu.serve.federation import FederationPlane, _WorkerHandle

    plane = FederationPlane(workers=0, spawn_workers=False)
    owner = _WorkerHandle(1)
    deposed = _WorkerHandle(2)
    plane.clusters["c"] = {
        "digest": "d", "spec": {}, "owner": 1, "epoch": 3,
        "last_seq": 10, "ticks": 0, "double_applied": 0, "moves": 0,
        "sweep_ms": None, "coldiff_bytes": None,
    }

    def stat(w, epoch, seq):
        plane._on_ingest_stat(w, {
            "cluster": "c", "epoch": epoch, "tick_seq": seq,
            "sweep_ms": 1.5, "coldiff_bytes": 64,
        })

    stat(owner, 3, 11)                 # applied
    ent = plane.clusters["c"]
    assert ent["ticks"] == 1 and ent["last_seq"] == 11
    assert ent["sweep_ms"] == 1.5 and ent["coldiff_bytes"] == 64
    stat(owner, 3, 11)                 # replayed seq -> double counted
    assert ent["double_applied"] == 1 and ent["ticks"] == 1
    stat(owner, 2, 12)                 # stale epoch -> dropped
    stat(deposed, 3, 12)               # wrong owner -> dropped
    assert plane.ingest_stale == 2
    assert ent["last_seq"] == 11 and ent["ticks"] == 1
    stat(owner, 3, 12)                 # next seq applies exactly once
    assert ent["ticks"] == 2 and ent["last_seq"] == 12
    status = plane.ingest_status()
    assert status["c"]["double_applied"] == 1
    assert "spec" not in status["c"]


def test_ingest_runner_resumes_seq_and_reports():
    from rca_tpu.serve.ingest import IngestRunner

    agent = SimpleNamespace(worker_id=9, conn=_FakeConn())
    runner = IngestRunner(agent, tick_s=0.01)
    try:
        runner.handle({
            "t": "ingest_assign", "cluster": "k0", "epoch": 4,
            "resume_seq": 7,
            "spec": {"services": 4, "seed": 1, "namespace": "synthetic"},
        })
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if len(agent.conn.sent) >= 3:
                break
            time.sleep(0.02)
        frames = [m for m in agent.conn.sent if m["t"] == "ingest_stat"]
        assert len(frames) >= 3, "runner never ticked"
        # resume semantics: the count CONTINUES the dead owner's seq
        assert frames[0]["tick_seq"] == 8
        seqs = [m["tick_seq"] for m in frames]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert all(m["cluster"] == "k0" and m["epoch"] == 4
                   for m in frames)
        assert all(m["coldiff_bytes"] > 0 for m in frames)
        assert all(
            isinstance(m["sweep_ms"], float) and m["sweep_ms"] >= 0
            and math.isfinite(m["sweep_ms"]) for m in frames
        )

        runner.handle({"t": "ingest_unassign", "cluster": "k0"})
        time.sleep(0.05)
        n = len([m for m in agent.conn.sent if m["t"] == "ingest_stat"])
        time.sleep(0.1)
        after = len(
            [m for m in agent.conn.sent if m["t"] == "ingest_stat"]
        )
        assert after <= n + 1, "unassigned cluster kept ticking"
    finally:
        runner.stop()


# -- 5. the platform-keyed shipped kernel cache ---------------------------


def test_shipped_kernel_cache_fallback(monkeypatch, tmp_path):
    """Cold start with no user cache reads the committed
    ``kernel_cache.<platform>.json``; a present user cache wins; a
    stale-header shipped cache re-times instead of poisoning."""
    from rca_tpu.engine.registry import KERNELS, KernelRegistry

    shipped = tmp_path / "shipped.json"
    winner = KERNELS[0]
    writer = KernelRegistry(cache_path=str(shipped))
    writer._store_cached("dense|64|cpu|", SimpleNamespace(
        winner=winner, timings_ms={winner: 1.0}, cost=None,
    ))
    assert shipped.exists()
    monkeypatch.setattr(
        "rca_tpu.config.shipped_kernel_cache_path",
        lambda: str(shipped),
    )

    # user cache missing -> the shipped row answers
    reg = KernelRegistry(cache_path=str(tmp_path / "user.json"))
    row = reg._load_cached("dense|64|cpu|")
    assert row is not None and row["winner"] == winner

    # user cache present -> it wins over the shipped row
    other = KERNELS[1]
    reg._store_cached("dense|64|cpu|", SimpleNamespace(
        winner=other, timings_ms={other: 0.5}, cost=None,
    ))
    row = reg._load_cached("dense|64|cpu|")
    assert row is not None and row["winner"] == other

    # stale shipped header (kernel edit / other platform): re-time
    data = json.loads(shipped.read_text())
    data["kernel_set"] = "stale"
    shipped.write_text(json.dumps(data))
    reg2 = KernelRegistry(cache_path=str(tmp_path / "nope.json"))
    assert reg2._load_cached("dense|64|cpu|") is None
