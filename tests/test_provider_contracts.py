"""Contract tests for the REAL provider bindings, without network or SDKs.

VERDICT r2 weak-spot 5: ``OpenAIProvider``/``AnthropicProvider`` message and
tool translation, the tool-call round trip, and quota-error classification
had zero coverage — every LLM test ran ``OfflineProvider`` subclasses, so a
signature drift in either SDK binding would ship silently.

These tests install **stub ``openai``/``anthropic`` modules** into
``sys.modules`` (the real SDKs are not in the image — reference anchor for
the wire behavior: /root/reference/utils/llm_client_improved.py:163-495).
Each stub records the exact request the binding sent, asserts nothing about
the network, and returns canned SDK-shaped responses (tool calls, quota
errors), driving the bindings end-to-end through ``LLMClient.analyze``.
"""

from __future__ import annotations

import json
import sys
import types
from typing import Any, Dict, List, Optional

import pytest

from rca_tpu.llm.client import LLMClient
from rca_tpu.llm.providers import (
    LLMQuotaExceeded,
    LLMUnavailable,
)
from rca_tpu.llm.tools import ToolSpec


# -- SDK stubs ---------------------------------------------------------------

class _Obj:
    """Attribute bag mimicking SDK response objects."""

    def __init__(self, **kw):
        self.__dict__.update(kw)


def _openai_text_response(text: str, finish: str = "stop"):
    return _Obj(choices=[_Obj(
        message=_Obj(content=text, tool_calls=None), finish_reason=finish,
    )])


def _openai_toolcall_response(calls: List[Dict[str, Any]]):
    return _Obj(choices=[_Obj(
        message=_Obj(
            content=None,
            tool_calls=[
                _Obj(id=c["id"], function=_Obj(
                    name=c["name"], arguments=json.dumps(c["arguments"]),
                ))
                for c in calls
            ],
        ),
        finish_reason="tool_calls",
    )])


class _FakeOpenAIClient:
    def __init__(self, replies: List[Any]):
        self.requests: List[Dict[str, Any]] = []
        self._replies = list(replies)
        outer = self

        class _Completions:
            def create(self, **kwargs):
                outer.requests.append(kwargs)
                reply = outer._replies.pop(0)
                if isinstance(reply, Exception):
                    raise reply
                return reply

        self.chat = _Obj(completions=_Completions())


def install_openai_stub(monkeypatch, replies: List[Any]) -> _FakeOpenAIClient:
    fake_client = _FakeOpenAIClient(replies)
    mod = types.ModuleType("openai")
    mod.OpenAI = lambda api_key: fake_client  # binding passes api_key only
    monkeypatch.setitem(sys.modules, "openai", mod)
    monkeypatch.setenv("OPENAI_API_KEY", "sk-test")
    return fake_client


def _anthropic_text_response(text: str, stop: str = "end_turn"):
    return _Obj(
        content=[_Obj(type="text", text=text)], stop_reason=stop,
    )


def _anthropic_tooluse_response(calls: List[Dict[str, Any]]):
    return _Obj(
        content=[
            _Obj(type="tool_use", id=c["id"], name=c["name"],
                 input=c["arguments"])
            for c in calls
        ],
        stop_reason="tool_use",
    )


class _FakeAnthropicClient:
    def __init__(self, replies: List[Any]):
        self.requests: List[Dict[str, Any]] = []
        self._replies = list(replies)
        outer = self

        class _Messages:
            def create(self, **kwargs):
                outer.requests.append(kwargs)
                reply = outer._replies.pop(0)
                if isinstance(reply, Exception):
                    raise reply
                return reply

        self.messages = _Messages()


def install_anthropic_stub(
    monkeypatch, replies: List[Any]
) -> _FakeAnthropicClient:
    fake_client = _FakeAnthropicClient(replies)
    mod = types.ModuleType("anthropic")
    mod.Anthropic = lambda api_key: fake_client
    monkeypatch.setitem(sys.modules, "anthropic", mod)
    monkeypatch.setenv("ANTHROPIC_API_KEY", "sk-ant-test")
    return fake_client


def _make_provider(name: str):
    # import AFTER stubs are installed; the classes import the SDK lazily
    # in __init__ so construction under the stub exercises the real path
    from rca_tpu.llm.providers import AnthropicProvider, OpenAIProvider

    return OpenAIProvider() if name == "openai" else AnthropicProvider()


ECHO_TOOL = ToolSpec(
    name="get_pod_logs",
    description="fetch pod logs",
    parameters={
        "type": "object",
        "properties": {"pod_name": {"type": "string"}},
        "required": ["pod_name"],
    },
    fn=lambda pod_name="": f"logs-of-{pod_name}: ERROR connection refused",
)


# -- OpenAI wire format ------------------------------------------------------

def test_openai_request_shape_and_tool_roundtrip(monkeypatch):
    fake = install_openai_stub(monkeypatch, [
        _openai_toolcall_response(
            [{"id": "call_1", "name": "get_pod_logs",
              "arguments": {"pod_name": "db-0"}}]
        ),
        _openai_text_response("db-0 is crash-looping"),
    ])
    client = LLMClient(provider=_make_provider("openai"))
    out = client.analyze(
        "why is db-0 failing?", tools=[ECHO_TOOL],
        system_prompt="you are an SRE",
    )

    # round trip: the tool executed and its output reached the final turn
    assert out["final_analysis"] == "db-0 is crash-looping"
    assert out["reasoning_steps"][0]["tool"] == "get_pod_logs"
    assert out["reasoning_steps"][0]["arguments"] == {"pod_name": "db-0"}

    first, second = fake.requests
    # OpenAI wire shape: tools wrapped as {"type": "function", "function"}
    assert first["tools"] == [{
        "type": "function",
        "function": ECHO_TOOL.schema(),
    }]
    assert first["messages"][0] == {
        "role": "system", "content": "you are an SRE",
    }
    assert first["messages"][1] == {
        "role": "user", "content": "why is db-0 failing?",
    }
    # second request replays the assistant tool call in OpenAI's nested
    # function shape with JSON-ENCODED arguments, then the tool result
    # bound by tool_call_id
    assistant = second["messages"][2]
    assert assistant["role"] == "assistant"
    assert assistant["tool_calls"] == [{
        "id": "call_1",
        "type": "function",
        "function": {
            "name": "get_pod_logs",
            "arguments": json.dumps({"pod_name": "db-0"}),
        },
    }]
    tool_msg = second["messages"][3]
    assert tool_msg["role"] == "tool"
    assert tool_msg["tool_call_id"] == "call_1"
    assert "logs-of-db-0" in tool_msg["content"]


def test_openai_json_mode_flag(monkeypatch):
    fake = install_openai_stub(monkeypatch, [
        _openai_text_response('{"a": 1}'),
    ])
    client = LLMClient(provider=_make_provider("openai"))
    out = client.generate_structured_output("give json")
    assert out == {"a": 1}
    assert fake.requests[0]["response_format"] == {"type": "json_object"}


def test_openai_malformed_tool_arguments_degrade_to_empty(monkeypatch):
    """SDKs deliver arguments as a JSON string; garbage must not crash the
    loop (providers._safe_json)."""
    resp = _Obj(choices=[_Obj(
        message=_Obj(content=None, tool_calls=[
            _Obj(id="x", function=_Obj(name="get_pod_logs",
                                       arguments="{not json")),
        ]),
        finish_reason="tool_calls",
    )])
    install_openai_stub(monkeypatch, [resp, _openai_text_response("done")])
    client = LLMClient(provider=_make_provider("openai"))
    out = client.analyze("q", tools=[ECHO_TOOL])
    assert out["final_analysis"] == "done"
    assert out["reasoning_steps"][0]["arguments"] == {}


# -- Anthropic wire format ---------------------------------------------------

def test_anthropic_request_shape_and_tool_roundtrip(monkeypatch):
    fake = install_anthropic_stub(monkeypatch, [
        _anthropic_tooluse_response(
            [{"id": "toolu_1", "name": "get_pod_logs",
              "arguments": {"pod_name": "db-0"}}]
        ),
        _anthropic_text_response("db-0 is crash-looping"),
    ])
    client = LLMClient(provider=_make_provider("anthropic"))
    out = client.analyze(
        "why is db-0 failing?", tools=[ECHO_TOOL],
        system_prompt="you are an SRE",
    )

    assert out["final_analysis"] == "db-0 is crash-looping"
    assert out["reasoning_steps"][0]["tool"] == "get_pod_logs"

    first, second = fake.requests
    # Anthropic wire shape: system is a TOP-LEVEL param, not a message
    assert first["system"] == "you are an SRE"
    assert all(m["role"] != "system" for m in first["messages"])
    # tools carry input_schema (not "parameters")
    assert first["tools"] == [{
        "name": "get_pod_logs",
        "description": "fetch pod logs",
        "input_schema": ECHO_TOOL.parameters,
    }]
    # the replayed assistant turn uses tool_use content blocks with DICT
    # input; the result returns as a user-role tool_result block
    assistant = second["messages"][1]
    assert assistant["role"] == "assistant"
    assert {"type": "tool_use", "id": "toolu_1", "name": "get_pod_logs",
            "input": {"pod_name": "db-0"}} in assistant["content"]
    result_msg = second["messages"][2]
    assert result_msg["role"] == "user"
    block = result_msg["content"][0]
    assert block["type"] == "tool_result"
    assert block["tool_use_id"] == "toolu_1"
    assert "logs-of-db-0" in block["content"]


def test_anthropic_json_mode_appends_instruction(monkeypatch):
    fake = install_anthropic_stub(monkeypatch, [
        _anthropic_text_response('```json\n{"b": 2}\n```'),
    ])
    client = LLMClient(provider=_make_provider("anthropic"))
    out = client.generate_structured_output("give json")
    # fenced-block rescue still applies to real-provider output
    assert out == {"b": 2}
    assert "valid JSON" in fake.requests[0]["system"]


def test_anthropic_multiblock_text_joined(monkeypatch):
    resp = _Obj(
        content=[
            _Obj(type="text", text="part one"),
            _Obj(type="text", text="part two"),
        ],
        stop_reason="end_turn",
    )
    install_anthropic_stub(monkeypatch, [resp])
    client = LLMClient(provider=_make_provider("anthropic"))
    assert client.generate_completion("q") == "part one\npart two"


# -- quota classification & failover ----------------------------------------

class _FakeRateLimitError(Exception):
    """Shaped like SDK rate-limit errors: classification is message-based
    (providers._classify_error), matching the reference's string checks
    (reference: utils/llm_client_improved.py:465-495)."""


@pytest.mark.parametrize("msg,expect_quota", [
    ("Error code: 429 - Rate limit reached for gpt-4o", True),
    ("You exceeded your current quota, please check your plan", True),
    ("rate_limit_error: Number of request tokens has exceeded", True),
    ("Error code: 500 - internal server error", False),
])
def test_quota_error_classification(monkeypatch, msg, expect_quota):
    install_openai_stub(monkeypatch, [_FakeRateLimitError(msg)])
    provider = _make_provider("openai")
    with pytest.raises(LLMUnavailable) as exc_info:
        provider.complete([{"role": "user", "content": "q"}])
    assert isinstance(exc_info.value, LLMQuotaExceeded) == expect_quota


def test_quota_failover_openai_to_anthropic(monkeypatch):
    """End-to-end runtime failover through LLMClient._complete: OpenAI 429s,
    the client fails over to Anthropic (stub) and sticks with it."""
    install_openai_stub(monkeypatch, [
        _FakeRateLimitError("Error code: 429 - rate limit"),
    ])
    install_anthropic_stub(monkeypatch, [
        _anthropic_text_response("anthropic took over"),
        _anthropic_text_response("still anthropic"),
    ])
    client = LLMClient(provider=_make_provider("openai"))
    assert client.generate_completion("q") == "anthropic took over"
    assert client.provider.name == "anthropic"  # sticky failover
    assert client.generate_completion("q2") == "still anthropic"


def test_quota_failover_lands_offline_when_all_keys_missing(monkeypatch):
    """Anthropic quota error with no other provider configured degrades to
    the deterministic offline provider instead of dying."""
    install_anthropic_stub(monkeypatch, [
        _FakeRateLimitError("rate_limit_error"),
    ])
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    client = LLMClient(provider=_make_provider("anthropic"))
    text = client.generate_completion("q")
    assert text.startswith("Offline analysis")
    assert client.provider.name == "offline"


def test_missing_key_raises_unavailable(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    from rca_tpu.llm.providers import OpenAIProvider

    with pytest.raises(LLMUnavailable):
        OpenAIProvider()
