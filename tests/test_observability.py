"""ISSUE 11: tracegraft — wire-to-device distributed tracing.

Covers the tentpole contracts:

- tracer core: seeded id determinism, with-block spans, complete-span
  records, parent propagation, the bounded ring buffer's drop counter;
- the ``RCA_TRACE=0`` zero-cost default: the null tracer records
  nothing, mints nothing, and rankings are BIT-identical with tracing
  on vs off;
- the serve path: one request through queue → batcher → dispatch →
  fetch yields one connected trace with correct parentage; a stolen
  request under replica kill KEEPS its trace (steal marker + root span,
  zero double completions);
- the gateway: ``X-RCA-Trace`` generated when absent and echoed either
  way, ``GET /v1/traces`` NDJSON + Perfetto-loadable Chrome export
  (golden-shape checked), per-tenant ``rca_request_duration_seconds``
  le-bucket histogram + SLO burn counters + gauge timestamps in
  ``/metrics``;
- streaming: tick spans in every health record, embedded in recorder
  frames, and ``rca replay --trace-out`` reconstructing the SAME
  timeline from the tape (byte parity with the live export);
- ``rca profile``: the opt-in jax.profiler capture with per-shape
  kernel attribution.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from rca_tpu.cluster.generator import (
    synthetic_cascade_arrays,
    synthetic_cascade_world,
)
from rca_tpu.config import ServeConfig, slo_ms, trace_buffer_cap, trace_enabled
from rca_tpu.engine.runner import GraphEngine
from rca_tpu.observability import (
    NULL_TRACER,
    SpanContext,
    Tracer,
    chrome_trace,
    ndjson_spans,
    recording_trace,
)
from rca_tpu.observability.export import DURATION_BUCKETS_S, LatencyHistogram
from rca_tpu.serve import ServeClient, ServeLoop, ServePool, ServeRequest
from rca_tpu.serve.metrics import ServeMetrics


@pytest.fixture(scope="module")
def engine():
    return GraphEngine()


@pytest.fixture(scope="module")
def case():
    return synthetic_cascade_arrays(24, n_roots=1, seed=3)


def _by_name(spans):
    out = {}
    for s in spans:
        out.setdefault(s["name"], []).append(s)
    return out


# -- config knobs (satellite) -------------------------------------------------

def test_trace_config_env_round_trip(monkeypatch):
    monkeypatch.setenv("RCA_TRACE", "1")
    monkeypatch.setenv("RCA_TRACE_BUFFER", "256")
    monkeypatch.setenv("RCA_SLO_MS", "250")
    assert trace_enabled() is True
    assert trace_buffer_cap() == 256
    assert slo_ms() == 250.0


def test_trace_config_defaults(monkeypatch):
    for name in ("RCA_TRACE", "RCA_TRACE_BUFFER", "RCA_SLO_MS"):
        monkeypatch.delenv(name, raising=False)
    # RCA_TRACE=0 is the documented zero-cost DEFAULT path
    assert trace_enabled() is False
    assert trace_buffer_cap() == 8192
    assert slo_ms() == 500.0


@pytest.mark.parametrize("name,bad", [
    ("RCA_TRACE", "maybe"),
    ("RCA_TRACE_BUFFER", "0"),
    ("RCA_TRACE_BUFFER", "abc"),
    ("RCA_SLO_MS", "0"),
    ("RCA_SLO_MS", "never"),
])
def test_trace_config_rejects_malformed(monkeypatch, name, bad):
    monkeypatch.setenv(name, bad)
    with pytest.raises(ValueError, match=name):
        {"RCA_TRACE": trace_enabled,
         "RCA_TRACE_BUFFER": trace_buffer_cap,
         "RCA_SLO_MS": slo_ms}[name]()


# -- span vocabulary ----------------------------------------------------------

def test_span_context_wire_round_trip():
    ctx = SpanContext("00ff00ff00ff00ff", "abcd1234")
    assert SpanContext.from_wire(ctx.to_wire()) == ctx


@pytest.mark.parametrize("bad", [
    None, "", "nodash", "a-b-c", "xyz!-1234", "-", "zz-zz",
])
def test_span_context_rejects_malformed(bad):
    # a garbage header starts a fresh trace; it must never raise
    assert SpanContext.from_wire(bad) is None


def test_tracer_with_block_and_record_parentage():
    t = Tracer(seed=0)
    root_ctx = t.new_context()
    with t.span("parent", parent=root_ctx) as sp:
        sp.set_attr("k", 1)
        child_ctx = sp.context
    t.record("child", 1.0, 2.0, parent=child_ctx)
    spans = t.spans()
    assert [s["name"] for s in spans] == ["parent", "child"]
    parent, child = spans
    assert parent["parent_id"] == root_ctx.span_id
    assert child["parent_id"] == parent["span_id"]
    assert child["trace_id"] == parent["trace_id"] == root_ctx.trace_id
    assert parent["attrs"] == {"k": 1}
    assert parent["end"] >= parent["start"]


def test_tracer_span_records_even_when_body_raises():
    t = Tracer(seed=0)
    with pytest.raises(RuntimeError):
        with t.span("boom"):
            raise RuntimeError("x")
    assert [s["name"] for s in t.spans()] == ["boom"]


def test_tracer_seeded_ids_are_deterministic():
    a, b = Tracer(seed=42), Tracer(seed=42)
    assert a.new_context().to_wire() == b.new_context().to_wire()


def test_ring_buffer_bounds_and_drop_counter():
    t = Tracer(seed=0, cap=64)
    for i in range(100):
        t.record(f"s{i}", float(i), float(i) + 1.0)
    stats = t.stats()
    assert stats["buffered"] == 64
    assert stats["recorded"] == 100
    assert stats["dropped"] == 36
    # oldest dropped, newest kept
    assert t.spans()[0]["name"] == "s36"
    assert t.spans()[-1]["name"] == "s99"


def test_null_tracer_is_zero_op():
    before = NULL_TRACER.stats()
    assert NULL_TRACER.new_context() is None
    assert NULL_TRACER.record("x", 0.0, 1.0) is None
    with NULL_TRACER.span("y") as sp:
        assert sp is None
    assert NULL_TRACER.spans() == []
    assert NULL_TRACER.stats() == before
    assert not NULL_TRACER.enabled


# -- export shapes ------------------------------------------------------------

def test_chrome_trace_golden_shape():
    t = Tracer(seed=0)
    ctx = t.new_context()
    t.record("serve.request", 10.0, 10.5, context=ctx)
    t.record("serve.queue", 10.0, 10.1, parent=ctx)
    trace = chrome_trace(t.spans())
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    assert trace["displayTimeUnit"] == "ms"
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    events = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(meta) == 1 and len(events) == 2       # one lane per trace
    for e in events:
        assert set(e) >= {"name", "cat", "ph", "pid", "tid", "ts", "dur",
                          "args"}
        assert e["args"]["trace_id"] == ctx.trace_id
    # rebased to the earliest span, microseconds
    assert events[0]["ts"] == 0.0
    assert events[0]["dur"] == pytest.approx(0.5e6)
    assert events[1]["args"]["parent_id"] == ctx.span_id
    # the whole object must be JSON-serializable (Perfetto loads it)
    json.loads(json.dumps(trace))


def test_ndjson_spans_one_object_per_line():
    t = Tracer(seed=0)
    t.record("a", 0.0, 1.0)
    t.record("b", 1.0, 2.0)
    lines = ndjson_spans(t.spans()).splitlines()
    assert [json.loads(line)["name"] for line in lines] == ["a", "b"]


def test_latency_histogram_buckets_are_cumulative():
    h = LatencyHistogram()
    h.record(0.004)
    h.record(0.3)
    h.record(99.0)   # beyond the last bucket: only +Inf (count) sees it
    d = h.to_dict()
    assert d["count"] == 3
    assert d["buckets"]["0.005"] == 1
    assert d["buckets"]["0.5"] == 2
    assert d["buckets"]["10.0"] == 2
    assert d["sum_s"] == pytest.approx(99.304)
    # cumulative: monotone non-decreasing along the ladder
    vals = [d["buckets"][str(le)] for le in DURATION_BUCKETS_S]
    assert vals == sorted(vals)


def test_serve_metrics_slo_burn_semantics():
    m = ServeMetrics(slo_ms_target=100.0)
    m.request_duration("t", 0.01, ok=True)    # fast + served: no burn
    m.request_duration("t", 0.5, ok=True)     # slow: burns
    m.request_duration("t", 0.01, ok=False)   # failed: burns at any speed
    snap = m.snapshot()
    assert snap["slo_breaches"] == {"t": 2}
    assert snap["slo_ms"] == 100.0
    assert snap["duration"]["t"]["count"] == 3


# -- the serve path: one connected trace --------------------------------------

def test_serve_loop_trace_is_connected(engine, case):
    tracer = Tracer(seed=1)
    loop = ServeLoop(engine=engine, tracer=tracer)
    with loop:
        client = ServeClient(loop)
        parent = tracer.new_context()
        resp = client.submit(
            case.features, case.dep_src, case.dep_dst, names=case.names,
            tenant="t", trace_parent=parent,
        ).result(300.0)
    assert resp.ok
    by = _by_name(tracer.spans())
    assert set(by) >= {"serve.request", "serve.queue", "serve.batch",
                       "serve.dispatch", "serve.fetch"}
    root = by["serve.request"][0]
    assert root["parent_id"] == parent.span_id
    assert root["attrs"]["status"] == "ok"
    for name in ("serve.queue", "serve.batch", "serve.dispatch",
                 "serve.fetch"):
        span = by[name][0]
        assert span["parent_id"] == root["span_id"], name
        assert span["trace_id"] == parent.trace_id
    # the per-request kernel attribution (ISSUE 11 satellite)
    assert by["serve.dispatch"][0]["attrs"]["kernel"] in ("xla", "pallas")
    # SLO telemetry flowed from the sink
    m = loop.metrics.summary()
    assert m["duration"]["t"]["count"] == 1
    assert m["slo_ms"] == slo_ms()


def test_trace_off_is_bit_parity_and_recordless(engine, case):
    """RCA_TRACE=0 (the null tracer) must not change a single ranking
    bit, and must record nothing."""
    def run(tracer):
        loop = ServeLoop(engine=engine, tracer=tracer)
        with loop:
            client = ServeClient(loop)
            reqs = [
                client.submit(case.features, case.dep_src, case.dep_dst,
                              names=case.names, tenant=f"t{i % 3}")
                for i in range(6)
            ]
            return [r.result(300.0) for r in reqs]

    off = run(NULL_TRACER)
    on = run(Tracer(seed=9))
    for a, b in zip(off, on):
        assert a.status == b.status == "ok"
        assert a.ranked == b.ranked   # exact float equality: bit parity
    assert NULL_TRACER.spans() == []
    assert NULL_TRACER.stats()["recorded"] == 0


# -- pool chaos: a stolen request keeps its trace -----------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _StubHandle:
    def __init__(self, requests, dispatched_at):
        self.requests = requests
        self.dispatched_at = dispatched_at


class _StubResult:
    def __init__(self, tag):
        self.ranked = [{"component": f"svc-{tag}", "score": 1.0}]
        self.engine = "stub"
        self.score = np.ones(1, np.float32)


class _StubDispatcher:
    engine = None
    engine_tag = "stub"

    def __init__(self):
        self.graphs = set()

    def has_graph(self, key):
        return key in self.graphs

    def dispatch(self, batch, now=None):
        self.graphs.add(batch[0].graph_key)
        return _StubHandle(list(batch), now if now is not None else 0.0)

    def fetch(self, handle):
        return [_StubResult(i) for i in range(len(handle.requests))]


def _req(tenant="t", n=8, seed=0, **kw) -> ServeRequest:
    rng = np.random.default_rng(seed)
    feats = rng.uniform(0, 1, (n, 4)).astype(np.float32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    return ServeRequest(tenant=tenant, features=feats, dep_src=src,
                        dep_dst=dst, k=3, **kw)


def test_stolen_request_keeps_its_trace():
    """Kill the replica holding staged work: the steal re-places the
    requests, the trace stays CONNECTED (same trace_id; a serve.steal
    marker parents onto the request's root), and completion telemetry
    records exactly once."""
    tracer = Tracer(seed=2)
    clock = _FakeClock()
    stubs = [_StubDispatcher(), _StubDispatcher()]
    pool = ServePool(
        dispatchers=stubs,
        config=ServeConfig(replicas=2, max_wait_us=0),
        clock=clock, tracer=tracer,
    )
    reqs = [_req("a", seed=i) for i in range(5)]
    for r in reqs:
        pool.submit(r)
    assert all(r.trace is not None for r in reqs)
    pool.route_once()
    victim = next(r for r in pool.replicas if r.occupancy())
    victim.kill()
    for _ in range(10):
        pool.run_once()
    assert all(r.result(timeout=0).status == "ok" for r in reqs)
    assert pool.sink.double_completions == 0
    by = _by_name(tracer.spans())
    assert len(by["serve.steal"]) == 5
    roots = {s["span_id"]: s for s in by["serve.request"]}
    assert len(roots) == 5
    for steal in by["serve.steal"]:
        root = roots[steal["parent_id"]]
        assert steal["trace_id"] == root["trace_id"]
        assert steal["attrs"]["from_replica"] == victim.replica_id
        assert steal["attrs"]["reason"] == "replica_death"
    # each stolen request still got batch+dispatch+fetch on the survivor
    for root in roots.values():
        children = [
            s for spans in by.values() for s in spans
            if s["parent_id"] == root["span_id"]
        ]
        names = {s["name"] for s in children}
        assert {"serve.queue", "serve.steal", "serve.batch",
                "serve.dispatch", "serve.fetch"} <= names
    # duration histogram recorded exactly once per request
    assert pool.metrics.snapshot()["duration"]["a"]["count"] == 5


# -- gateway: header contract, /v1/traces, /metrics ---------------------------

@pytest.fixture()
def gateway(engine, case):
    from rca_tpu.gateway import GatewayClient, GatewayServer

    tracer = Tracer(seed=3)
    loop = ServeLoop(engine=engine, tracer=tracer).start()
    gw = GatewayServer(loop, port=0, wall=lambda: 1700000000.0).start()
    client = GatewayClient(gw.host, gw.port, timeout_s=300.0)
    yield gw, client, tracer
    gw.close()
    loop.stop()


def test_gateway_trace_generated_and_connected(gateway, case):
    """The acceptance gate: one POST /v1/analyze yields ONE connected
    trace (gateway → queue → batch → dispatch → fetch, >= 6 spans,
    correct parentage) retrievable via /v1/traces in both formats."""
    gw, client, tracer = gateway
    code, body, headers = client.analyze(
        case.features, case.dep_src, case.dep_dst, names=case.names,
        tenant="acme",
    )
    assert code == 200
    trace_id = body["trace_id"]
    assert trace_id
    echoed = {k.lower(): v for k, v in headers.items()}["x-rca-trace"]
    assert echoed.split("-")[0] == trace_id
    spans = client.traces(trace_id=trace_id)
    assert len(spans) >= 6
    by = _by_name(spans)
    gw_span = by["gateway.analyze"][0]
    root = by["serve.request"][0]
    assert gw_span["parent_id"] is None          # fresh trace: no header
    assert root["parent_id"] == gw_span["span_id"]
    for name in ("serve.queue", "serve.batch", "serve.dispatch",
                 "serve.fetch"):
        assert by[name][0]["parent_id"] == root["span_id"], name
    assert gw_span["attrs"]["code"] == 200
    # Perfetto-loadable Chrome export of the same trace
    ct = client.traces(trace_id=trace_id, fmt="chrome")
    assert {"traceEvents", "displayTimeUnit"} <= set(ct)
    assert sum(1 for e in ct["traceEvents"] if e["ph"] == "X") >= 6


def test_gateway_echoes_caller_trace_context(gateway, case):
    gw, client, tracer = gateway
    code, body, headers = client.analyze(
        case.features, case.dep_src, case.dep_dst, names=case.names,
        tenant="acme", trace="feedfacefeedface-12345678",
    )
    assert code == 200
    assert body["trace_id"] == "feedfacefeedface"
    spans = client.traces(trace_id="feedfacefeedface")
    gw_span = _by_name(spans)["gateway.analyze"][0]
    # the gateway span parents onto the WIRE context
    assert gw_span["parent_id"] == "12345678"


def test_gateway_metrics_histogram_and_timestamps(gateway, case):
    gw, client, tracer = gateway
    client.analyze(case.features, case.dep_src, case.dep_dst,
                   names=case.names, tenant="acme")
    text = client.metrics_text()
    assert "# TYPE rca_request_duration_seconds histogram" in text
    assert ('rca_request_duration_seconds_bucket{le="+Inf",tenant="acme"}'
            in text)
    assert 'rca_request_duration_seconds_count{tenant="acme"}' in text
    assert 'rca_request_duration_seconds_sum{tenant="acme"}' in text
    assert "# TYPE rca_slo_breaches_total counter" in text
    assert "rca_slo_target_ms" in text
    # gauges carry the wall seam's ms timestamp (proper exposition)
    assert "rca_gateway_up 1 1700000000000" in text
    # cumulative bucket sanity on the scraped text
    counts = {}
    for line in text.splitlines():
        if line.startswith("rca_request_duration_seconds_bucket"):
            le = line.split('le="')[1].split('"')[0]
            counts[le] = int(float(line.rsplit(" ", 1)[1]))
    assert counts["+Inf"] == max(counts.values())


def test_gateway_traces_empty_when_disabled(engine, case):
    from rca_tpu.gateway import GatewayClient, GatewayServer

    loop = ServeLoop(engine=engine, tracer=NULL_TRACER)
    loop.start()
    gw = GatewayServer(loop, port=0).start()
    try:
        client = GatewayClient(gw.host, gw.port, timeout_s=300.0)
        code, body, headers = client.analyze(
            case.features, case.dep_src, case.dep_dst,
            names=case.names, tenant="t",
        )
        assert code == 200
        assert "trace_id" not in body      # nothing minted, nothing faked
        assert client.traces() == []
        # a caller-sent context is still echoed verbatim (correlation
        # survives even a trace-disabled hop)
        _c, _b, h2 = client.analyze(
            case.features, case.dep_src, case.dep_dst,
            names=case.names, tenant="t", trace="abcd1234abcd1234-aabbccdd",
        )
        echoed = {k.lower(): v for k, v in h2.items()}["x-rca-trace"]
        assert echoed == "abcd1234abcd1234-aabbccdd"
    finally:
        gw.close()
        loop.stop()


# -- streaming: spans in health records + timeline reconstruction -------------

def test_streaming_spans_and_recording_timeline_parity(tmp_path):
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession
    from rca_tpu.replay import Recorder

    tracer = Tracer(seed=4)
    world = synthetic_cascade_world(20, n_roots=1, seed=5, namespace="ns")
    rec_path = str(tmp_path / "rec")
    rec = Recorder(rec_path, mode="stream")
    live = LiveStreamingSession(MockClusterClient(world), "ns", k=3,
                                recorder=rec, tracer=tracer)
    for _ in range(3):
        out = live.poll()
    rec.close()
    spans = out["health"]["spans"]
    assert [s["name"] for s in spans] == [
        "tick", "tick.capture", "tick.dispatch", "tick.fetch",
    ]
    tick = spans[0]
    assert tick["attrs"]["kernel_path"] in ("xla", "pallas")
    for child in spans[1:]:
        assert child["parent_id"] == tick["span_id"]
        assert child["trace_id"] == tick["trace_id"]
    # one trace per session: every tick shares the trace id
    assert len({s["trace_id"] for s in tracer.spans()}) == 1
    # timeline reconstruction from the TAPE == the live export
    assert recording_trace(rec_path) == chrome_trace(tracer.spans())


def test_replay_trace_out_cli(tmp_path):
    from rca_tpu.cli import main
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession
    from rca_tpu.replay import Recorder

    world = synthetic_cascade_world(16, n_roots=1, seed=6, namespace="ns")
    rec_path = str(tmp_path / "rec")
    rec = Recorder(rec_path, mode="stream")
    live = LiveStreamingSession(MockClusterClient(world), "ns", k=3,
                                recorder=rec, tracer=Tracer(seed=5))
    live.poll()
    live.poll()
    rec.close()
    out_path = str(tmp_path / "trace.json")
    assert main(["replay", rec_path, "--trace-out", out_path,
                 "--compact"]) == 0
    with open(out_path, encoding="utf-8") as f:
        trace = json.load(f)
    assert len([e for e in trace["traceEvents"] if e["ph"] == "X"]) == 8


def test_recording_without_spans_yields_empty_trace(tmp_path):
    """A pre-tracing (or RCA_TRACE=0) recording reconstructs to an empty
    timeline — and the CLI says so with a nonzero exit."""
    from rca_tpu.cli import main
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession
    from rca_tpu.replay import Recorder

    world = synthetic_cascade_world(16, n_roots=1, seed=6, namespace="ns")
    rec_path = str(tmp_path / "rec")
    rec = Recorder(rec_path, mode="stream")
    live = LiveStreamingSession(MockClusterClient(world), "ns", k=3,
                                recorder=rec, tracer=NULL_TRACER)
    live.poll()
    rec.close()
    assert recording_trace(rec_path)["traceEvents"] == []
    assert main(["replay", rec_path, "--trace-out",
                 str(tmp_path / "t.json"), "--compact"]) == 1


# -- rca profile (opt-in capture) ---------------------------------------------

def test_profile_capture(tmp_path):
    from rca_tpu.observability.profile import profile_ticks

    tracer = Tracer(seed=6)
    summary = profile_ticks(str(tmp_path / "prof"), ticks=2, services=16,
                            seed=7, tracer=tracer)
    assert summary["ticks"] == 2
    assert list(summary["kernel_by_shape"].values())[0] in (
        "xla", "pallas",
    )
    assert summary["profile_files"] >= 1     # the jax.profiler dump exists
    assert summary["spans_recorded"] >= 8    # 2 ticks x 4 spans
    from rca_tpu.observability.spans import profiling_active

    assert not profiling_active()            # flag cleared after capture
