"""Learned propagation weights: loss decreases, held-out accuracy holds,
orbax checkpoint round-trips, engine loads weights from RCA_WEIGHTS."""

import numpy as np
import pytest

from rca_tpu.engine.propagate import default_params
from rca_tpu.engine.train import (
    TrainConfig,
    hit_at_1,
    load_params,
    make_dataset,
    params_to_pytree,
    pytree_to_params,
    sample_generator_kwargs,
    save_params,
    shippability_report,
    train,
)

# train on the hard modes — the defaults already near-ace "standard", so
# that regime has no loss headroom for the 10% improvement assertion
CFG = TrainConfig(
    n_services=64, n_cases=16, iters=60, lr=0.05, seed=1,
    modes=("adversarial", "crashing_victims"),
)


@pytest.fixture(scope="module")
def trained():
    return train(CFG)


def test_dataset_shapes():
    feats, edges, roots = make_dataset(CFG)
    B, S1, C = feats.shape
    assert B == CFG.n_cases and S1 == CFG.n_services + 1
    assert edges.shape[0] == B and edges.shape[1] == 2
    # padded edges self-loop on the dummy slot
    assert int(edges.max()) <= CFG.n_services
    assert roots.shape == (B, S1)
    assert (np.asarray(roots).sum(axis=1) >= 1).all()


def test_param_pytree_roundtrip():
    p = default_params()
    q = pytree_to_params(params_to_pytree(p), steps=p.steps)
    np.testing.assert_allclose(
        q.anomaly_weights, p.anomaly_weights, atol=1e-3
    )
    assert abs(q.decay - p.decay) < 1e-3
    # beta's domain is (0, inf): the v3 default 1.6 must survive the
    # round trip (a sigmoid parameterization silently clamps it to ~1.0)
    assert abs(q.impact_bonus - p.impact_bonus) < 1e-3
    assert p.impact_bonus > 1.0


def test_domain_randomization_samples_ranges():
    cfg = TrainConfig()
    rng = np.random.default_rng(0)
    draws = [sample_generator_kwargs(cfg, rng) for _ in range(50)]
    decays = {d["decay"] for d in draws}
    deps = {d["max_deps"] for d in draws}
    assert len(decays) == 50  # continuous knobs actually vary
    assert deps == {2, 3, 4}  # inclusive integer range fully covered
    for d in draws:
        assert cfg.dr_decay[0] <= d["decay"] <= cfg.dr_decay[1]
        assert cfg.dr_dropout_keep[0] <= d["dropout_keep"] <= cfg.dr_dropout_keep[1]


def test_shippability_gate():
    """Defaults pass the ship gate; round-2-style degenerate weights
    (decay collapsed, CRASH dropped from hard evidence) are refused on
    sanity alone."""
    import dataclasses

    from rca_tpu.features.schema import SvcF

    report = shippability_report(default_params(), trials_per_setting=3)
    assert report["ships"], report
    assert report["fixtures"]["five_svc_ok"]

    p = default_params()
    hw = list(p.hard_weights)
    hw[SvcF.CRASH] = 0.05
    degenerate = dataclasses.replace(
        p, decay=0.02, hard_weights=tuple(hw)
    )
    bad = shippability_report(degenerate, trials_per_setting=2)
    assert not bad["ships"]
    assert not bad["sanity"]["decay_ok"]
    assert not bad["sanity"]["hard_crash_ok"]


def test_shippability_gate_rejects_channel_zeroing():
    """A fit that zeroes the image/config/pending/oom channels (what
    crash-only training actually produced in round 3) is sane by the
    scalar checks and competitive on crash cascades — the per-archetype
    fixture check is what catches it."""
    import dataclasses

    from rca_tpu.features.schema import SvcF

    p = default_params()
    aw, hw = list(p.anomaly_weights), list(p.hard_weights)
    for ch in (SvcF.IMAGE, SvcF.CONFIG, SvcF.PENDING, SvcF.OOM):
        aw[ch] = 0.02
        hw[ch] = 0.02
    zeroed = dataclasses.replace(
        p, anomaly_weights=tuple(aw), hard_weights=tuple(hw)
    )
    report = shippability_report(zeroed, trials_per_setting=2)
    assert not report["fixtures"]["archetypes_ok"], report["fixtures"]
    assert not report["ships"]
    # and the sanity checks alone would NOT have caught it
    assert report["sanity"]["decay_ok"]
    assert report["sanity"]["hard_crash_ok"]


def test_training_reduces_loss_and_keeps_accuracy(trained):
    params, history = trained
    assert history[-1] < history[0] * 0.9, history[:3] + history[-3:]
    assert all(0.0 < w < 1.0 for w in params.anomaly_weights)
    acc = hit_at_1(params, CFG)
    assert acc >= 0.9
    # not worse than the hand-set defaults on the same held-out seeds
    base = hit_at_1(default_params(CFG.steps), CFG)
    assert acc >= base - 0.1


def test_checkpoint_roundtrip_and_engine_env(tmp_path, trained, monkeypatch):
    params, _ = trained
    path = str(tmp_path / "ckpt")
    save_params(params, path)
    loaded = load_params(path)
    np.testing.assert_allclose(
        loaded.anomaly_weights, params.anomaly_weights, atol=1e-6
    )
    assert loaded.steps == params.steps
    assert abs(loaded.decay - params.decay) < 1e-6

    from rca_tpu.engine import GraphEngine

    monkeypatch.setenv("RCA_WEIGHTS", path)
    eng = GraphEngine()
    np.testing.assert_allclose(
        eng.params.anomaly_weights, params.anomaly_weights, atol=1e-6
    )


def test_json_checkpoint_roundtrip_and_dispatch(tmp_path, trained):
    """The packaged-artifact JSON format round-trips, records provenance,
    and load_params dispatches on file-vs-directory."""
    import json

    from rca_tpu.engine.train import load_params_json, save_params_json

    params, _ = trained
    path = str(tmp_path / "weights.json")
    save_params_json(params, path, provenance={"note": "unit test"})
    loaded = load_params_json(path)
    np.testing.assert_allclose(
        loaded.anomaly_weights, params.anomaly_weights, atol=1e-6
    )
    assert abs(loaded.impact_bonus - params.impact_bonus) < 1e-6
    # the generic loader picks the JSON path for plain files
    also = load_params(path)
    assert also == loaded
    assert json.load(open(path))["provenance"]["note"] == "unit test"


def test_default_weight_resolution(tmp_path, monkeypatch):
    """Resolution order: RCA_WEIGHTS=off -> hand-set defaults;
    unset -> the packaged checkpoint when present."""
    from rca_tpu.config import RCAConfig
    from rca_tpu.engine import train as train_mod
    from rca_tpu.engine.runner import resolve_params

    cfg = RCAConfig()
    monkeypatch.setenv("RCA_WEIGHTS", "off")
    assert resolve_params(cfg, None) == default_params(cfg.propagation_steps)

    # fake packaged artifact: unset env must pick it up
    import dataclasses

    p = default_params()
    marked = dataclasses.replace(p, decay=0.777)
    fake = tmp_path / "default_weights.json"
    from rca_tpu.engine.train import save_params_json

    save_params_json(marked, str(fake))
    monkeypatch.delenv("RCA_WEIGHTS", raising=False)
    monkeypatch.setattr(train_mod, "PACKAGED_WEIGHTS", fake)
    got = resolve_params(cfg, None)
    assert abs(got.decay - 0.777) < 1e-9
    # explicit params always win
    assert resolve_params(cfg, p) == p


def test_config_steps_governs_loaded_checkpoints(tmp_path, monkeypatch):
    """propagation_steps is a runtime depth knob: a loaded checkpoint's
    recorded steps (training metadata) must not silently disable it
    (round-4 review finding)."""
    import dataclasses

    from rca_tpu.config import RCAConfig
    from rca_tpu.engine import train as train_mod
    from rca_tpu.engine.runner import resolve_params
    from rca_tpu.engine.train import save_params_json

    marked = dataclasses.replace(default_params(steps=8), decay=0.777)
    fake = tmp_path / "default_weights.json"
    save_params_json(marked, str(fake))
    monkeypatch.delenv("RCA_WEIGHTS", raising=False)
    monkeypatch.setattr(train_mod, "PACKAGED_WEIGHTS", fake)
    got = resolve_params(RCAConfig(propagation_steps=4), None)
    assert got.steps == 4                   # config knob honored
    assert abs(got.decay - 0.777) < 1e-9    # weights still the artifact's
