"""COO-scatter vs capped-ELL edge layouts must produce identical scores."""

import numpy as np
import pytest

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.engine import GraphEngine
from rca_tpu.engine.ell import EllGraph, build_ell_segments, propagate_ell
from rca_tpu.engine.propagate import default_params, propagate_jit


@pytest.mark.parametrize("n,n_roots,cap", [(300, 2, 32), (300, 2, 2), (50, 1, 1)])
def test_ell_matches_scatter(n, n_roots, cap):
    """Exact agreement for any overflow regime (cap=1/2 forces heavy use of
    the overflow path)."""
    case = synthetic_cascade_arrays(n, n_roots=n_roots, seed=3)
    p = default_params()
    aw, hw = p.weight_arrays()
    n_pad = n + 1
    f = np.zeros((n_pad, case.features.shape[1]), np.float32)
    f[:n] = case.features

    a1, h1, u1, m1, s1 = propagate_jit(
        f, case.dep_src, case.dep_dst, aw, hw,
        p.steps, p.decay, p.explain_strength, p.impact_bonus,
    )
    ell = EllGraph.build(n_pad, case.dep_src, case.dep_dst, width_cap=cap)
    a2, h2, u2, m2, s2 = propagate_ell(
        f, ell.up.idx, ell.up.mask, ell.up.ovf_seg, ell.up.ovf_other,
        ell.down.idx, ell.down.mask, ell.down.ovf_seg, ell.down.ovf_other,
        aw, hw, p.steps, p.decay, p.explain_strength, p.impact_bonus,
    )
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=1e-5)


def test_ell_engine_path_env_switch(monkeypatch):
    case = synthetic_cascade_arrays(200, n_roots=1, seed=0)
    eng = GraphEngine()
    r_coo = eng.analyze_arrays(case.features, case.dep_src, case.dep_dst, k=3)
    monkeypatch.setenv("RCA_EDGE_LAYOUT", "ell")
    r_ell = eng.analyze_arrays(case.features, case.dep_src, case.dep_dst, k=3)
    assert [x["component"] for x in r_coo.ranked] == [
        x["component"] for x in r_ell.ranked
    ]
    np.testing.assert_allclose(r_coo.score, r_ell.score, atol=1e-6)


def test_build_ell_segments_empty_and_overflow():
    empty = build_ell_segments(
        np.zeros(0, np.int32), np.zeros(0, np.int32), 8
    )
    assert empty.n_overflow == 0
    assert empty.mask.sum() == 0

    # one hub with 10 in-edges, cap 4 -> 6 overflow
    seg = np.zeros(10, np.int32)
    other = np.arange(10, dtype=np.int32)
    s = build_ell_segments(seg, other, 12, width_cap=4)
    assert s.idx.shape[1] == 4
    assert s.n_overflow == 6
    assert set(s.ovf_other[:6].tolist()) == set(range(4, 10))
