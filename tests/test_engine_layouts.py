"""COO-scatter vs capped-ELL edge layouts must produce identical scores."""

import numpy as np
import pytest

from rca_tpu.cluster.generator import synthetic_cascade_arrays
from rca_tpu.engine import GraphEngine
from rca_tpu.engine.ell import EllGraph, build_ell_segments, propagate_ell
from rca_tpu.engine.propagate import default_params, propagate_jit


@pytest.mark.parametrize("n,n_roots,cap", [(300, 2, 32), (300, 2, 2), (50, 1, 1)])
def test_ell_matches_scatter(n, n_roots, cap):
    """Exact agreement for any overflow regime (cap=1/2 forces heavy use of
    the overflow path)."""
    case = synthetic_cascade_arrays(n, n_roots=n_roots, seed=3)
    p = default_params()
    aw, hw = p.weight_arrays()
    n_pad = n + 1
    f = np.zeros((n_pad, case.features.shape[1]), np.float32)
    f[:n] = case.features

    a1, h1, u1, m1, s1 = propagate_jit(
        f, case.dep_src, case.dep_dst, aw, hw,
        p.steps, p.decay, p.explain_strength, p.impact_bonus,
    )
    ell = EllGraph.build(n_pad, case.dep_src, case.dep_dst, width_cap=cap)
    a2, h2, u2, m2, s2 = propagate_ell(
        f, ell.up.idx, ell.up.mask, ell.up.ovf_seg, ell.up.ovf_other,
        ell.down.idx, ell.down.mask, ell.down.ovf_seg, ell.down.ovf_other,
        aw, hw, p.steps, p.decay, p.explain_strength, p.impact_bonus,
    )
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=1e-5)


def test_ell_engine_path_env_switch(monkeypatch):
    """All three layouts (default hybrid, pure coo, pure ell) agree."""
    case = synthetic_cascade_arrays(200, n_roots=1, seed=0)
    eng = GraphEngine()
    monkeypatch.setenv("RCA_EDGE_LAYOUT", "hybrid")  # pin: ambient env must not skip the hybrid leg
    r_hybrid = eng.analyze_arrays(case.features, case.dep_src, case.dep_dst, k=3)
    monkeypatch.setenv("RCA_EDGE_LAYOUT", "coo")
    r_coo = eng.analyze_arrays(case.features, case.dep_src, case.dep_dst, k=3)
    monkeypatch.setenv("RCA_EDGE_LAYOUT", "ell")
    r_ell = eng.analyze_arrays(case.features, case.dep_src, case.dep_dst, k=3)
    assert [x["component"] for x in r_coo.ranked] == [
        x["component"] for x in r_ell.ranked
    ]
    np.testing.assert_allclose(r_coo.score, r_ell.score, atol=1e-6)
    # hybrid's up-scan reorders only MAX reductions -> bit-identical to coo
    assert [x["component"] for x in r_hybrid.ranked] == [
        x["component"] for x in r_coo.ranked
    ]
    np.testing.assert_array_equal(r_hybrid.score, r_coo.score)
    np.testing.assert_array_equal(r_hybrid.upstream, r_coo.upstream)


def test_hybrid_up_table_overflow_regime():
    """A service with more dependencies than the width cap (8) exercises the
    hybrid up-scan's overflow scatter; scores must stay bit-identical."""
    import jax.numpy as jnp

    from rca_tpu.engine.propagate import propagate
    from rca_tpu.engine.runner import build_up_ell

    rng = np.random.default_rng(0)
    n, n_pad = 40, 41
    # node 0 depends on 20 services (overflow), the rest form a chain
    src = np.concatenate([np.zeros(20, np.int32),
                          np.arange(1, n - 1, dtype=np.int32)])
    dst = np.concatenate([np.arange(1, 21, dtype=np.int32),
                          np.arange(2, n, dtype=np.int32)])
    from rca_tpu.features.schema import NUM_SERVICE_FEATURES

    f = np.zeros((n_pad, NUM_SERVICE_FEATURES), np.float32)
    f[:n] = rng.uniform(0, 1, (n, NUM_SERVICE_FEATURES)).astype(np.float32)
    p = default_params()
    aw, hw = p.weight_arrays()

    args = (aw, hw, p.steps, p.decay, p.explain_strength, p.impact_bonus)
    coo = propagate(jnp.asarray(f), src, dst, *args, n_live=n)
    hyb = propagate(
        jnp.asarray(f), src, dst, *args, n_live=n,
        up_ell=build_up_ell(n_pad, src, dst),
    )
    for x, y in zip(coo, hyb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_build_ell_segments_empty_and_overflow():
    empty = build_ell_segments(
        np.zeros(0, np.int32), np.zeros(0, np.int32), 8
    )
    assert empty.n_overflow == 0
    assert empty.mask.sum() == 0

    # one hub with 10 in-edges, cap 4 -> 6 overflow
    seg = np.zeros(10, np.int32)
    other = np.arange(10, dtype=np.int32)
    s = build_ell_segments(seg, other, 12, width_cap=4)
    assert s.idx.shape[1] == 4
    assert s.n_overflow == 6
    assert set(s.ovf_other[:6].tolist()) == set(range(4, 10))


def test_segscan_down_layout_matches_coo(monkeypatch):
    """The Pallas segmented-scan down-scan (VERDICT r3 item 1) must agree
    with the COO scatter to float tolerance across modes and tiers —
    exercised hermetically on CPU via the kernel's interpret mode."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import GraphEngine

    monkeypatch.setenv("SEGSCAN_INTERPRET", "1")  # kernel runs anywhere
    for n, mode in ((180, "standard"), (700, "adversarial")):
        c = synthetic_cascade_arrays(n, n_roots=2, seed=7, mode=mode,
                                     fault_mix="mixed")
        monkeypatch.setenv("RCA_SEGSCAN", "0")
        base = GraphEngine().analyze_case(c, k=5)
        monkeypatch.setenv("RCA_SEGSCAN", "1")
        seg = GraphEngine().analyze_case(c, k=5)
        np.testing.assert_allclose(
            seg.score, base.score, rtol=1e-5, atol=1e-6,
            err_msg=f"segscan diverged at n={n} mode={mode}",
        )
        assert seg.top_components() == base.top_components()


def test_segscan_streaming_session_matches_scatter(monkeypatch):
    """Streaming ticks with the segscan down-scan engaged match the
    scatter path (delta + quiet ticks)."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine.streaming import StreamingSession

    monkeypatch.setenv("SEGSCAN_INTERPRET", "1")
    c = synthetic_cascade_arrays(300, n_roots=2, seed=9)
    names = [f"s{i}" for i in range(c.n)]

    def run(env):
        monkeypatch.setenv("RCA_SEGSCAN", env)
        sess = StreamingSession(
            names, c.dep_src, c.dep_dst, c.features.shape[1], k=5
        )
        sess.set_all(c.features)
        outs = [sess.tick()]
        sess.update(3, np.clip(c.features[3] + 0.5, 0, 1))
        outs.append(sess.tick())
        outs.append(sess.tick())  # quiet
        return [
            [(r["component"], round(r["score"], 5)) for r in o["ranked"]]
            for o in outs
        ]

    assert run("0") == run("1")
