"""Headline benchmark: RCA graph-inference latency on a 2k-service cascade.

Measures the north-star metric (BASELINE.json): END-TO-END latency (dispatch
+ device execution + result fetch) of the jit'd explain-away propagation +
top-k ranking over a 2,000-service synthetic fault cascade (3 concurrent
roots), and whether the true roots are ranked top-1/top-k.  Baseline target:
< 150 ms on TPU v5e-1 with top-1 hit.  ``vs_baseline`` = 150 / measured_ms
(higher is better; >1 beats target).

Timing semantics (round-2 correction): every measurement synchronizes by
FETCHING a result slice (``jax.device_get``), never by ``block_until_ready``
alone — on tunneled TPU backends (axon) block_until_ready can return at
enqueue time, which is how round 1 printed a 0.027 ms "latency" that was
really dispatch-queue insertion.  The per-sync host<->device round trip is
measured separately (``sync_floor_ms``, ~90 ms through the tunnel, ~0 on a
host-attached chip) and cancels out of the in-jit amortized numbers, which
time an R-rep and a 2R-rep loop and report the marginal (t_2R - t_R)/R —
pure device compute per inference, immune to the floor's jitter.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The JSON line is the SOLE stdout output — everything else any stage prints
(training progress, warmup chatter, library warnings) is routed to stderr,
so ``bench.py | tail -1`` (and the harness's "last stdout line" parse)
always sees valid JSON instead of ``"parsed": null``.
"""

import json
import os
import sys


def chaos_metrics(seed: int = 7, ticks: int = 100) -> dict:
    """Resilience row for the bench trajectory (``--chaos``): a seeded
    chaos soak on the 50-service fixture — regression here means a fault
    path stopped absorbing (see RESILIENCE.md; full knobs on the CLI:
    ``python -m rca_tpu chaos``)."""
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.resilience.chaos import ChaosConfig, run_chaos_soak

    summary = run_chaos_soak(
        lambda: synthetic_cascade_world(50, n_roots=1, seed=0),
        "synthetic", seed=seed, ticks=ticks, config=ChaosConfig(seed=seed),
    )
    return {
        "ticks": summary["ticks"],
        "uncaught_exceptions": summary["uncaught_exceptions"],
        "all_classes_observed": summary["all_classes_observed"],
        "parity_ok": summary["parity_ok"],
        "parity_ticks_checked": summary["parity_ticks_checked"],
        "degraded_ticks": summary["degraded_ticks"],
        "sanitized_rows_total": summary["sanitized_rows_total"],
        "resyncs_expired": summary["resyncs_expired"],
        "resyncs_topology": summary["resyncs_topology"],
    }


def attribution_metrics(engine) -> dict:
    """causelens cost rows (ISSUE 14): what an attribution PASS costs
    per shape (first call = compile + run, steady = the cached
    executables), reported from the kernel registry's ``attribution``
    variant rows so bench, ``rca kernels``, and ``/metrics`` agree by
    construction.  The explain-OFF overhead claim is cross-round: the
    default path computes nothing (attribution is lazy), so the
    explain-off serve p50 rides the bench_guard gate (<5% on
    ``attribution.explain_off_request_ms_p50``) against the last
    committed round."""
    import time as _time

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine.registry import kernel_table

    per_shape = {}
    for n in (256, 2000):
        c = synthetic_cascade_arrays(n, n_roots=1, seed=11)

        def one():
            res = engine.analyze_arrays(
                c.features, c.dep_src, c.dep_dst, c.names, k=5,
            )
            t0 = _time.perf_counter()
            prov = res.attribution()
            ms = (_time.perf_counter() - t0) * 1e3
            return ms, prov

        first_ms, prov = one()
        steady = min(one()[0] for _ in range(3))
        block = prov["attribution"]
        per_shape[str(n)] = {
            "first_ms": round(first_ms, 3),
            "steady_ms": round(steady, 3),
            "k": block["k"], "topm": block["topm"],
            "reconstruction_err_max": max(
                (cand["reconstruction_error"]
                 for cand in block["candidates"]), default=0.0,
            ),
        }
    rows = [
        {
            "n_pad": r["n_pad"], "e_pad": r["e_pad"],
            "winner": r["winner"],
            "attribution_ms": (r.get("timings_ms") or {}).get(
                "attribution"
            ),
        }
        for r in kernel_table() if r["variant"] == "attribution"
    ]
    return {"per_shape": per_shape, "registry_rows": rows}


def replay_metrics(n_services: int = 50, ticks: int = 40) -> dict:
    """Flight-recorder row (ISSUE 5): what recording COSTS (tick-time
    overhead vs an unrecorded twin and log bytes/tick) and what replay
    BUYS (ticks/s re-driving the real engine from the log, vs the live
    capture's tick rate) — plus the parity bit, because a recorder whose
    replays diverge is measuring nothing."""
    import shutil
    import tempfile
    import time

    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession
    from rca_tpu.replay import Recorder, replay_stream

    def run_session(recorder=None, use_columnar=True):
        world = synthetic_cascade_world(n_services, n_roots=1, seed=0)
        sess = LiveStreamingSession(
            MockClusterClient(world), "synthetic", k=5,
            topology_check_every=10, recorder=recorder,
            use_columnar=use_columnar,
        )
        times = []
        rng = np.random.default_rng(1)
        for t in range(ticks):
            if t % 3 == 0:
                # journaled churn so recorded ticks carry real deltas
                i = int(rng.integers(0, n_services))
                name = f"pod-svc-{i:05d}" if n_services > 5 else "pod-0"
                world.touch("pod_metrics", "synthetic", name)
            t0 = time.perf_counter()
            sess.poll()
            times.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(times))

    plain_ms = run_session()
    tmp = tempfile.mkdtemp(prefix="rca_replay_bench_")
    rec_path = f"{tmp}/rec"
    rec_path_dict = f"{tmp}/rec_dict"
    try:
        recorder = Recorder(rec_path)
        recorded_ms = run_session(recorder)
        recorder.close()
        bytes_per_tick = recorder.bytes_written / max(1, ticks)
        # dict-path twin (ISSUE 10): same world/schedule recorded through
        # the per-object capture path — the coldiff frames' byte and
        # overhead delta is reported side by side
        plain_dict_ms = run_session(use_columnar=False)
        recorder_d = Recorder(rec_path_dict)
        recorded_dict_ms = run_session(recorder_d, use_columnar=False)
        recorder_d.close()
        bytes_per_tick_dict = recorder_d.bytes_written / max(1, ticks)
        t0 = time.perf_counter()
        report = replay_stream(rec_path)
        replay_s = time.perf_counter() - t0
        return {
            "ticks": ticks,
            "tick_ms_unrecorded": round(plain_ms, 3),
            "tick_ms_recorded": round(recorded_ms, 3),
            "record_overhead_pct": round(
                100.0 * (recorded_ms - plain_ms) / max(plain_ms, 1e-9), 1
            ),
            "record_overhead_pct_dict": round(
                100.0 * (recorded_dict_ms - plain_dict_ms)
                / max(plain_dict_ms, 1e-9), 1
            ),
            "log_bytes_per_tick": round(bytes_per_tick, 1),
            "log_bytes_per_tick_dict": round(bytes_per_tick_dict, 1),
            "coldiff_bytes_ratio": round(
                bytes_per_tick / max(bytes_per_tick_dict, 1e-9), 3
            ),
            "replay_ticks_per_sec": round(
                report["ticks_replayed"] / max(replay_s, 1e-9), 1
            ),
            "live_ticks_per_sec": round(1e3 / max(recorded_ms, 1e-9), 1),
            "replay_parity_ok": report["parity_ok"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def columnar_capture_metrics(n_services: int = 20_000,
                             pods_per_service: int = 5) -> dict:
    """Columnar world state at 100k pods (ISSUE 10 tentpole gate).

    Capture-layer measurements (no engine: the tick executables are
    benched elsewhere and a 20k-service XLA compile would only blur the
    capture numbers this section exists to isolate):

    - steady columnar sweep (capture + vectorized extract) vs ONE dict
      sweep over the same world — the O(dirty rows) vs O(objects) claim;
    - busy capture after journaled churn, and the quiet-feed drain cost
      (sweep-vs-quiet ratio);
    - recorded bytes/tick for busy columnar captures (coldiff frames);
    - BIT parity columnar-vs-dict asserted on the full 100k-pod
      FeatureSet in this same run (a fast capture that changed one bit
      would be measuring nothing).
    """
    import shutil
    import tempfile
    import time

    import numpy as np

    from rca_tpu.cluster.columnar import ColumnarClientState
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.features.extract import extract_features
    from rca_tpu.replay import Recorder

    ns = "col100k"
    t0 = time.perf_counter()
    world = synthetic_cascade_world(
        n_services, n_roots=3, seed=2, namespace=ns,
        pods_per_service=pods_per_service,
    )
    build_s = time.perf_counter() - t0
    n_pods = sum(len(v) for v in world.pods.values())
    client = MockClusterClient(world)
    state = ColumnarClientState()

    t0 = time.perf_counter()
    snap = ClusterSnapshot.capture(client, ns, columnar_state=state)
    first_capture_s = time.perf_counter() - t0  # includes the table build

    sweep_ms = []
    for _ in range(3):
        t0 = time.perf_counter()
        snap = ClusterSnapshot.capture(client, ns, columnar_state=state)
        fs_col = extract_features(snap)
        sweep_ms.append((time.perf_counter() - t0) * 1e3)

    # ONE dict sweep for the ratio + the parity gate (bitwise, full set)
    t0 = time.perf_counter()
    snap_d = ClusterSnapshot.capture(client, ns, columnar=False)
    fs_dict = extract_features(snap_d)
    dict_sweep_ms = (time.perf_counter() - t0) * 1e3
    parity_ok = (
        fs_col.pod_names == fs_dict.pod_names
        and fs_col.service_names == fs_dict.service_names
        and fs_col.pod_features.tobytes() == fs_dict.pod_features.tobytes()
        and fs_col.service_features.tobytes()
        == fs_dict.service_features.tobytes()
        and fs_col.memb_pod.tobytes() == fs_dict.memb_pod.tobytes()
        and fs_col.memb_svc.tobytes() == fs_dict.memb_svc.tobytes()
        and fs_col.pod_service.tobytes() == fs_dict.pod_service.tobytes()
        and fs_col.pod_node.tobytes() == fs_dict.pod_node.tobytes()
    )
    assert parity_ok, "columnar-vs-dict bit parity FAILED at 100k pods"

    # quiet feed drain (what a no-change poll costs the capture layer)
    cursor = client.watch_changes(ns, None)["cursor"]
    quiet_ms = []
    for _ in range(5):
        t0 = time.perf_counter()
        resp = client.watch_changes(ns, cursor)
        cursor = resp["cursor"]
        quiet_ms.append((time.perf_counter() - t0) * 1e3)

    # busy capture + recorded bytes/tick: journaled churn, coldiff frames
    rng = np.random.default_rng(3)
    tmp = tempfile.mkdtemp(prefix="rca_col_bench_")
    try:
        recorder = Recorder(f"{tmp}/rec")
        rec_client = recorder.wrap_client(client)
        rec_state = ColumnarClientState()
        recorder.begin_tick(0)
        snap_b = ClusterSnapshot.capture(
            rec_client, ns, columnar_state=rec_state,
        )
        bootstrap_bytes = recorder.bytes_written
        busy_ms = []
        pod_names_flat = [
            p["metadata"]["name"] for p in world.pods[ns]
        ]
        busy_ticks = 10
        for t in range(1, busy_ticks + 1):
            for _ in range(20):
                world.touch(
                    "pod_metrics", ns,
                    pod_names_flat[int(rng.integers(0, n_pods))],
                )
            recorder.begin_tick(t)
            t0 = time.perf_counter()
            # traces carry forward on un-journaled busy polls — the live
            # session's contract; re-fetching (and re-recording) the 20k
            # trace payloads per tick would swamp the coldiff bytes
            snap_b = ClusterSnapshot.capture(
                rec_client, ns, columnar_state=rec_state,
                traces_from=snap_b.traces,
            )
            extract_features(snap_b)
            busy_ms.append((time.perf_counter() - t0) * 1e3)
        recorder.close()
        delta_bytes = recorder.bytes_written - bootstrap_bytes
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    sweep = float(np.median(sweep_ms))
    quiet = float(np.median(quiet_ms))
    return {
        "n_pods": int(n_pods),
        "n_services": int(n_services),
        "world_build_s": round(build_s, 2),
        "table_build_first_capture_s": round(first_capture_s, 2),
        "sweep_capture_ms": round(sweep, 2),
        "dict_sweep_capture_ms": round(dict_sweep_ms, 2),
        "sweep_speedup_vs_dict": round(dict_sweep_ms / max(sweep, 1e-9), 1),
        "busy_capture_ms_20dirty": round(float(np.median(busy_ms)), 2),
        "quiet_feed_drain_ms": round(quiet, 3),
        "sweep_vs_quiet_ratio": round(sweep / max(quiet, 1e-3), 1),
        "record_bytes_per_tick": round(delta_bytes / busy_ticks, 1),
        "record_bootstrap_bytes": int(bootstrap_bytes),
        "parity_ok_100k": bool(parity_ok),
    }


def planet_capture_metrics(clusters: int = 10,
                           n_services: int = 20_000,
                           pods_per_service: int = 5,
                           busy_ticks: int = 5) -> dict:
    """The 1M-pod sustained soak (ISSUE 17 tentpole leg): capture 1M
    pods AGGREGATE across ``clusters`` simulated clusters (100k pods
    each), per-cluster mirrors swept SEQUENTIALLY — the federated-ingest
    shape, where each cluster's columnar mirror is owned and ticked
    independently (one worker never holds ten 100k worlds at once, and
    neither does this bench: build, soak, free, next).

    Per cluster, through the LIVE columnar adapter
    (:class:`~rca_tpu.cluster.live_columnar.LiveColumnarFeed` — the
    watch-pump path the real ``K8sApiClient`` uses, not the mock's
    native columnar master):

    - steady sweep tick (capture + vectorized extract, no churn);
    - busy tick after 20 journaled touches, with the coldiff payload
      bytes that tick shipped;
    - quiet tick (the no-change drain a poll costs);
    - live-vs-dict BIT parity asserted in-run on the first cluster's
      full 100k-pod FeatureSet (a fast sweep that moved one bit would
      be measuring nothing).

    ``RCA_PLANET_CLUSTERS`` shrinks the fleet for smoke runs."""
    import gc
    import json as _json
    import os as _os
    import time

    import numpy as np

    from rca_tpu.cluster.columnar import ColumnarClientState
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.live_columnar import LiveColumnarFeed
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.features.extract import extract_features

    clusters = int(_os.environ.get("RCA_PLANET_CLUSTERS", clusters))

    class _LiveShim:
        """The mock client with its native columnar master REPLACED by
        the live watch-pump adapter — what a real apiserver-backed
        capture pays."""

        def __init__(self, inner, ns):
            self._inner = inner
            self._feed = LiveColumnarFeed(inner, ns)

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def get_columnar(self, namespace, cursor=None):
            return self._feed.payload(cursor)

        def close(self):
            self._feed.close()

    def _bytes(payload) -> int:
        try:
            return len(_json.dumps(
                payload, default=lambda o: (
                    o.tolist() if hasattr(o, "tolist") else str(o)
                ),
            ))
        except Exception:
            return 0

    rng = np.random.default_rng(17)
    per_cluster = []
    sweep_all, busy_all, quiet_all, coldiff_all = [], [], [], []
    total_pods = 0
    build_s_total = 0.0
    parity_checked = False
    soak_t0 = time.perf_counter()
    for j in range(clusters):
        ns = f"planet{j}"
        t0 = time.perf_counter()
        world = synthetic_cascade_world(
            n_services, n_roots=3, seed=100 + j, namespace=ns,
            pods_per_service=pods_per_service,
        )
        build_s = time.perf_counter() - t0
        build_s_total += build_s
        n_pods = sum(len(v) for v in world.pods.values())
        total_pods += n_pods
        client = _LiveShim(MockClusterClient(world), ns)
        state = ColumnarClientState()
        t0 = time.perf_counter()
        snap = ClusterSnapshot.capture(client, ns, columnar_state=state)
        first_s = time.perf_counter() - t0

        sweep_ms = []
        for _ in range(3):
            t0 = time.perf_counter()
            snap = ClusterSnapshot.capture(
                client, ns, columnar_state=state,
                traces_from=snap.traces,
            )
            fs_live = extract_features(snap)
            sweep_ms.append((time.perf_counter() - t0) * 1e3)

        if not parity_checked:
            # ONE dict sweep for the in-run parity bit: the live
            # adapter's 100k-pod FeatureSet vs the dict path, bitwise
            snap_d = ClusterSnapshot.capture(
                client._inner, ns, columnar=False,
                traces_from=snap.traces,
            )
            fs_dict = extract_features(snap_d)
            parity_ok = (
                fs_live.pod_names == fs_dict.pod_names
                and fs_live.service_names == fs_dict.service_names
                and fs_live.pod_features.tobytes()
                == fs_dict.pod_features.tobytes()
                and fs_live.service_features.tobytes()
                == fs_dict.service_features.tobytes()
                and fs_live.memb_pod.tobytes() == fs_dict.memb_pod.tobytes()
                and fs_live.memb_svc.tobytes() == fs_dict.memb_svc.tobytes()
                and fs_live.pod_service.tobytes()
                == fs_dict.pod_service.tobytes()
                and fs_live.pod_node.tobytes() == fs_dict.pod_node.tobytes()
            )
            assert parity_ok, (
                "planet_capture: live-vs-dict bit parity FAILED at 100k"
            )
            parity_checked = True

        pod_names_flat = [p["metadata"]["name"] for p in world.pods[ns]]
        busy_ms, coldiff = [], []
        byte_cursor = client.get_columnar(ns, None).get("cursor")
        for _ in range(busy_ticks):
            for _t in range(20):
                world.touch(
                    "pod_metrics", ns,
                    pod_names_flat[int(rng.integers(0, n_pods))],
                )
            t0 = time.perf_counter()
            snap = ClusterSnapshot.capture(
                client, ns, columnar_state=state,
                traces_from=snap.traces,
            )
            extract_features(snap)
            busy_ms.append((time.perf_counter() - t0) * 1e3)
            diff = client.get_columnar(ns, byte_cursor)
            byte_cursor = diff.get("cursor", byte_cursor)
            coldiff.append(_bytes(diff))

        quiet_ms = []
        for _ in range(3):
            t0 = time.perf_counter()
            p = client.get_columnar(ns, state.cursor)
            quiet_ms.append((time.perf_counter() - t0) * 1e3)
            state.apply(ns, p)

        per_cluster.append({
            "cluster": j,
            "n_pods": int(n_pods),
            "world_build_s": round(build_s, 2),
            "first_capture_s": round(first_s, 2),
            "sweep_tick_ms": round(float(np.median(sweep_ms)), 2),
            "busy_tick_ms": round(float(np.median(busy_ms)), 2),
            "quiet_tick_ms": round(float(np.median(quiet_ms)), 3),
            "coldiff_bytes_per_tick": round(
                float(np.median(coldiff)), 1
            ),
        })
        sweep_all.extend(sweep_ms)
        busy_all.extend(busy_ms)
        quiet_all.extend(quiet_ms)
        coldiff_all.extend(coldiff)
        # free before the next cluster: the soak's aggregate is 1M pods
        # CAPTURED, not 1M pods resident
        client.close()
        del world, client, state, snap, fs_live, pod_names_flat
        gc.collect()

    def pct(xs, q):
        return round(float(np.percentile(np.asarray(xs), q)), 2)

    return {
        "clusters": int(clusters),
        "n_pods_aggregate": int(total_pods),
        "soak_wall_s": round(time.perf_counter() - soak_t0, 1),
        "world_build_s_total": round(build_s_total, 1),
        "sweep_tick_ms_p50": pct(sweep_all, 50),
        "sweep_tick_ms_p99": pct(sweep_all, 99),
        "busy_tick_ms_p50": pct(busy_all, 50),
        "busy_tick_ms_p99": pct(busy_all, 99),
        "quiet_tick_ms_p50": pct(quiet_all, 50),
        "quiet_tick_ms_p99": pct(quiet_all, 99),
        "coldiff_bytes_per_tick_p50": pct(coldiff_all, 50),
        "parity_ok_live_vs_dict_100k": True,  # asserted above
        "per_cluster": per_cluster,
    }


def lint_metrics() -> dict:
    """graftlint wall time (ISSUE 4 satellite; ISSUE 7 extensions): the
    analyzer gates every PR, so its cost is tracked like any other
    latency — if a new rule makes ``rca lint`` crawl, this row catches it
    before the gate starts getting skipped.  ``findings`` must stay 0
    (the repo ships clean with an empty baseline; ANALYSIS.md).

    ISSUE 7 adds the top-3 slowest rules, a ``concurrency`` sub-row
    (the gravelock model's size: functions traversed, lock-order graph
    shape) and the rsan shim's per-acquire overhead vs a bare lock —
    the number that justifies "zero-cost when off, cheap enough for
    every stress run when on"."""
    import time

    from rca_tpu.analysis import run_lint
    from rca_tpu.analysis.concurrency import model_for, rsan
    from rca_tpu.analysis.core import parse_cache_stats, repo_root

    pc0 = parse_cache_stats()
    result = run_lint()
    top3 = sorted(result.per_rule_ms.items(), key=lambda kv: -kv[1])[:3]

    model = model_for(repo_root())
    stats = model.stats()
    # shared-parse-cache effectiveness across the lint + model build
    # (ISSUE 19 satellite: one ast.parse per file per run)
    pc1 = parse_cache_stats()
    pc_hits = pc1["hits"] - pc0["hits"]
    pc_misses = pc1["misses"] - pc0["misses"]

    # rsan overhead: uncontended acquire/release, bare vs sanitized
    def time_lock(lock, n=20_000):
        t0 = time.perf_counter()
        for _ in range(n):
            with lock:
                pass
        return (time.perf_counter() - t0) / n * 1e9  # ns/acquire

    import threading

    bare_ns = time_lock(threading.Lock())
    was = rsan.enabled()
    rsan.enable()
    try:
        sanitized_ns = time_lock(rsan.SanitizedLock("bench._lock"))
    finally:
        rsan.RSAN.reset()
        if not was:
            rsan.disable()

    return {
        "wall_ms": round(result.wall_ms, 1),
        "files": result.files_scanned,
        "findings": len(result.findings),
        "parse_cache_hit_rate": round(
            pc_hits / max(pc_hits + pc_misses, 1), 3
        ),
        "slowest_rules": [
            {"rule": name, "ms": round(ms, 1)} for name, ms in top3
        ],
        "slowest_rule": top3[0][0],
        "slowest_rule_ms": round(top3[0][1], 1),
        "concurrency": {
            "functions": stats["functions"],
            "functions_traversed": stats["functions_traversed"],
            "thread_roots": len(stats["thread_roots"]),
            "locks": stats["locks"],
            "lock_graph_nodes": stats["lock_graph_nodes"],
            "lock_graph_edges": stats["lock_graph_edges"],
            "rsan_overhead_pct": round(
                100.0 * (sanitized_ns - bare_ns) / max(bare_ns, 1e-9), 1
            ),
            "rsan_acquire_ns": round(sanitized_ns, 1),
            "bare_acquire_ns": round(bare_ns, 1),
        },
    }


def sync_floor_metrics(sync_floor_ms, device_compute_ms_2k) -> dict:
    """``sync_floor`` section (ISSUE 6): what one-shot analysis pays
    AROUND device compute, and how much of it resident sessions erase.

    A/B at 2k and 10k services: the same 16-dirty-row request stream
    served by a resident engine (delta scatter into the pinned buffer,
    top-k fetch) vs a restaging engine (full padded upload per request).
    On a tunneled TPU the difference is the ~100x floor itself; on this
    sync-floor-free bench host compute dominates e2e, so the section also
    reports the isolated STAGING floor at 2k (e2e minus the amortized
    in-jit device compute) — the component the resident path actually
    targets, and the number that converges to the e2e ratio once a
    tunnel's per-byte cost multiplies it.  Byte accounting comes from the
    resident session's own upload/fetch counters (host-side, exact)."""
    import time

    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import GraphEngine

    def run_mode(case, resident, n_reqs=16, dirty=16, seed=0):
        eng = GraphEngine(resident=resident)
        rng = np.random.default_rng(seed)
        f = case.features.copy()
        n, C = f.shape
        # warm: compile + first staging, then one delta-tier request so
        # no measured request pays a compile
        eng.analyze_arrays(f, case.dep_src, case.dep_dst, case.names, k=5)
        rows = rng.integers(0, n, dirty)
        f[rows] = np.clip(f[rows] + 0.01, 0, 1)
        eng.analyze_arrays(f, case.dep_src, case.dep_dst, case.names, k=5)
        times = []
        for _ in range(n_reqs):
            rows = rng.integers(0, n, dirty)
            f[rows] = np.clip(
                f[rows]
                + rng.uniform(-0.05, 0.05, (dirty, C)).astype(np.float32),
                0, 1,
            )
            t0 = time.perf_counter()
            eng.analyze_arrays(
                f, case.dep_src, case.dep_dst, case.names, k=5
            )
            times.append((time.perf_counter() - t0) * 1e3)
        stats = (
            eng._resident_cache.stats() if resident else None
        )
        return float(np.median(times)), stats

    from rca_tpu.config import RCAConfig, bucket_for

    buckets = RCAConfig().shape_buckets
    out = {"sync_floor_ms": round(sync_floor_ms, 3)}
    for n in (2000, 10000):
        case = synthetic_cascade_arrays(n, n_roots=3, seed=0)
        res_ms, stats = run_mode(case, resident=True)
        full_ms, _ = run_mode(case, resident=False)
        tag = f"{n // 1000}k"
        C = case.features.shape[1]
        staged_bytes = bucket_for(n + 1, buckets) * C * 4
        # per-request bytes on the DELTA path (the steady state): total
        # uploads minus the one-time full staging, over delta requests
        delta_bytes = int(
            (stats["upload_bytes"] - staged_bytes)
            / max(stats["delta_requests"], 1)
        )
        out[f"resident_e2e_ms_{tag}"] = round(res_ms, 3)
        out[f"restaged_e2e_ms_{tag}"] = round(full_ms, 3)
        out[f"resident_vs_restaged_{tag}"] = round(
            res_ms / max(full_ms, 1e-9), 3
        )
        out[f"upload_bytes_per_request_resident_{tag}"] = delta_bytes
        # restaged upload = the full padded matrix every request (exact)
        out[f"upload_bytes_per_request_restaged_{tag}"] = staged_bytes
        out[f"fetch_bytes_per_request_{tag}"] = int(
            stats["fetch_bytes"] / max(stats["requests"], 1)
        )
        out[f"delta_requests_{tag}"] = stats["delta_requests"]
    # the isolated staging floor at 2k: e2e minus pure device compute —
    # what the resident path erases (null when compute was unmeasurable)
    if device_compute_ms_2k is not None:
        res_floor = max(out["resident_e2e_ms_2k"] - device_compute_ms_2k,
                        0.0)
        full_floor = max(out["restaged_e2e_ms_2k"] - device_compute_ms_2k,
                         0.0)
        out["resident_floor_ms_2k"] = round(res_floor, 3)
        out["restaged_floor_ms_2k"] = round(full_floor, 3)
        out["floor_ratio_2k"] = (
            round(res_floor / full_floor, 3) if full_floor > 0 else None
        )
    return out


def observability_metrics(engine, case, concurrency: int = 16,
                          per_worker: int = 4) -> dict:
    """``observability`` (ISSUE 11 + 12): what tracing AND kernelscope
    cost when they are ON, and that they cost NOTHING when off.

    - **overhead**: closed-loop request p50 at concurrency 16 through a
      ServeLoop holding the NULL tracer with kernelscope disarmed vs the
      same loop with a live tracer + the recompile watchdog — the
      combined target is < 5% p50;
    - **drop rate**: spans shed by a deliberately tiny ring buffer under
      the same load (saturation drops history, never blocks);
    - **profile capture**: wall cost of an `rca profile` 20-tick window.
    """
    import tempfile
    import threading
    import time

    from rca_tpu.config import ServeConfig
    from rca_tpu.observability import NULL_TRACER, Tracer
    from rca_tpu.observability.profile import profile_ticks
    from rca_tpu.serve import (
        BatchDispatcher,
        ServeClient,
        ServeLoop,
        ServeRequest,
    )

    cfg = ServeConfig(max_batch=16, max_wait_us=2000, queue_cap=256)

    # warm every pow2 batch width BEFORE either leg: the engine's jit
    # cache is shared, so neither measurement pays a compile (the A/B
    # must compare tracing, not cache luck)
    warm_disp = BatchDispatcher(engine)
    w = 1
    while w <= cfg.max_batch:
        # twice per width: the first full-stages (and pins the resident
        # base), the second rides the delta-scatter executable — both
        # paths the measured loops will hit
        for _ in range(2):
            warm_disp.fetch(warm_disp.dispatch([
                ServeRequest(tenant="warm", features=case.features,
                             dep_src=case.dep_src, dep_dst=case.dep_dst,
                             k=5)
                for _ in range(w)
            ]))
        w *= 2

    def closed_loop_p50(tracer, kernelscope: bool = False) -> tuple:
        loop = ServeLoop(engine=engine, config=cfg, tracer=tracer,
                         kernelscope=kernelscope)
        lat_ms = []
        lock = threading.Lock()
        scope = {}
        with loop:
            client = ServeClient(loop)
            # warm the batch widths this load can hit
            client.submit(case.features, case.dep_src, case.dep_dst,
                          tenant="warm", k=5).result(600.0)

            def worker(w: int) -> None:
                for j in range(per_worker):
                    t1 = time.perf_counter()
                    resp = client.submit(
                        case.features, case.dep_src, case.dep_dst,
                        tenant=f"t{w}", k=5,
                    ).result(600.0)
                    dt = (time.perf_counter() - t1) * 1e3
                    if resp.ok:
                        with lock:
                            lat_ms.append(dt)

            threads = [
                threading.Thread(target=worker, args=(w,))
                for w in range(concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # kernelscope snapshot BEFORE the loop stops (the monitor
            # disarms with it)
            scope = loop.recompile_monitor.snapshot()
        lat_ms.sort()
        p50 = lat_ms[len(lat_ms) // 2] if lat_ms else None
        return p50, len(lat_ms), scope

    # alternate the legs and keep each mode's best p50 (the PERF.md
    # amortized-min methodology): on this 1-core host run-order effects
    # (allocator/cache warmth) are larger than the tracing delta itself,
    # so a single off-then-on pass reports warmth, not tracing.  The ON
    # leg arms tracing AND the kernelscope recompile watchdog (ISSUE 12)
    # so the <5% target covers the combined observability stack.
    tracer_on = Tracer(seed=0)
    offs, ons = [], []
    n_on = 0
    scope_recompiles = 0
    # 3 reps, not 2: on this 1-core host a 2-rep alternation still lands
    # ~15% orderings often enough to matter; the third rep's minimum
    # reliably converges to the noise floor (round-12 measurement note)
    for _rep in range(3):
        p50, _n, _ = closed_loop_p50(NULL_TRACER, kernelscope=False)
        offs.append(p50)
        p50, n, scope = closed_loop_p50(tracer_on, kernelscope=True)
        ons.append(p50)
        n_on += n
        scope_recompiles += scope.get("recompiles", 0)
    p50_off = min(p for p in offs if p is not None)
    p50_on = min(p for p in ons if p is not None)

    # drop rate under saturation: the same load into a 64-span buffer
    sat_tracer = Tracer(seed=1, cap=64)
    closed_loop_p50(sat_tracer)
    sat = sat_tracer.stats()

    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        prof = profile_ticks(tmp, ticks=20, services=100, seed=7,
                             tracer=Tracer(seed=2))
        profile_ms = (time.perf_counter() - t0) * 1e3

    overhead_pct = (
        round((p50_on - p50_off) / max(p50_off, 1e-9) * 100.0, 1)
        if p50_on is not None and p50_off is not None else None
    )
    return {
        "concurrency": concurrency,
        "requests": concurrency * per_worker,
        "request_ms_p50_trace_off": round(p50_off, 3),
        "request_ms_p50_trace_on": round(p50_on, 3),
        # tracing + kernelscope combined (ISSUE 12): the ON leg carries
        # both; the recompile count doubles as the serve-path watchdog
        # gate (0 = no cache-key drift under concurrency-16 load)
        "observability_overhead_pct_p50": overhead_pct,
        "kernelscope_recompiles": scope_recompiles,
        "spans_per_request": round(
            tracer_on.stats()["recorded"] / max(n_on, 1), 1
        ),
        "saturation_buffer_cap": sat["cap"],
        "saturation_dropped": sat["dropped"],
        "span_drop_rate_pct": round(
            sat["dropped"] / max(sat["recorded"], 1) * 100.0, 1
        ),
        "profile_capture_ms_20t": round(profile_ms, 1),
        "profile_ms_per_tick": prof["ms_per_tick"],
        "kernel_by_shape_profiled": prof["kernel_by_shape"],
    }


def serve_throughput_metrics(
    engine, case, concurrency: int = 16, n_requests: int = 64,
) -> dict:
    """``serve_throughput_2k`` (ISSUE 3): analyses/sec for ``n_requests``
    concurrent analyze requests through the serving scheduler
    (rca_tpu/serve — continuous shape-bucketed batching) vs. the same
    requests served one-by-one through the solo analyze boundary (what
    pre-serve entry points pay: one device dispatch + one sync each).
    Every batch-width executable the run can hit is warmed first, so both
    figures measure steady-state serving, not compiles."""
    import threading
    import time

    import numpy as np

    from rca_tpu.config import ServeConfig
    from rca_tpu.serve import (
        BatchDispatcher,
        ServeClient,
        ServeLoop,
        ServeRequest,
    )

    cfg = ServeConfig(max_batch=16, max_wait_us=2000, queue_cap=256)
    rng = np.random.default_rng(0)
    feats = [
        np.clip(
            case.features
            + rng.uniform(0, 0.02, case.features.shape).astype(np.float32),
            0, 1,
        )
        for _ in range(n_requests)
    ]

    # serialized baseline: the pre-serve world — each request owns the
    # device for one dispatch + one sync, strictly one after another
    engine.analyze_arrays(feats[0], case.dep_src, case.dep_dst, k=5)  # warm
    t0 = time.perf_counter()
    for f in feats:
        engine.analyze_arrays(f, case.dep_src, case.dep_dst, k=5)
    serial_s = time.perf_counter() - t0

    # warm every power-of-two batch width up to max_batch (the dispatcher
    # pads widths to pow2, so these five executables cover any flush)
    warm_disp = BatchDispatcher(engine)
    w = 1
    while w <= cfg.max_batch:
        warm_disp.fetch(warm_disp.dispatch([
            ServeRequest(tenant="warm", features=feats[0],
                         dep_src=case.dep_src, dep_dst=case.dep_dst, k=5)
            for _ in range(w)
        ]))
        w *= 2

    loop = ServeLoop(engine=engine, config=cfg)
    responses = [None] * n_requests
    with loop:
        client = ServeClient(loop)

        def submitter(worker: int) -> None:
            reqs = [
                (i, client.submit(
                    feats[i], case.dep_src, case.dep_dst,
                    tenant=f"t{worker}", k=5,
                ))
                for i in range(worker, n_requests, concurrency)
            ]
            for i, req in reqs:
                responses[i] = req.result(600.0)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=submitter, args=(w,))
            for w in range(concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        serve_s = time.perf_counter() - t0

        # request-latency SLO rows (ISSUE 6 satellite): a CLOSED-LOOP
        # phase — each worker submits one request and waits for its
        # response before the next — so per-request wall time is a clean
        # submit→completion latency sample, not inflated by a worker
        # waiting on earlier futures.  p50/p99 over all samples.
        slo_ms = []
        slo_lock = threading.Lock()

        def slo_worker(worker: int, per_worker: int = 4) -> None:
            for j in range(per_worker):
                t1 = time.perf_counter()
                resp = client.submit(
                    feats[(worker + j) % n_requests],
                    case.dep_src, case.dep_dst,
                    tenant=f"slo{worker}", k=5,
                ).result(600.0)
                dt = (time.perf_counter() - t1) * 1e3
                if resp.ok:
                    with slo_lock:
                        slo_ms.append(dt)

        threads = [
            threading.Thread(target=slo_worker, args=(w,))
            for w in range(concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    n_ok = sum(1 for r in responses if r is not None and r.ok)
    queue_ms = sorted(r.queue_ms for r in responses if r is not None and r.ok)

    def pct(q):
        if not queue_ms:
            return None
        return round(queue_ms[min(len(queue_ms) - 1,
                                  int(round(q * (len(queue_ms) - 1))))], 3)

    m = loop.metrics.summary()
    serial_aps = n_requests / max(serial_s, 1e-9)
    serve_aps = n_ok / max(serve_s, 1e-9)
    return {
        "concurrency": concurrency,
        "requests": n_requests,
        "all_ok": n_ok == n_requests,
        "serial_analyses_per_sec": round(serial_aps, 1),
        "serve_analyses_per_sec": round(serve_aps, 1),
        "speedup_vs_serial": round(serve_aps / max(serial_aps, 1e-9), 2),
        "device_batches": loop.device_batches,
        "batch_occupancy_mean": m["batch_occupancy_mean"],
        "batch_occupancy_p50": m["batch_occupancy_p50"],
        "batch_occupancy_max": m["batch_occupancy_max"],
        "queue_ms_p50": pct(0.50),
        "queue_ms_p99": pct(0.99),
        # closed-loop submit->completion latency (the SLO a caller sees)
        "request_ms_p50": (
            round(float(np.percentile(slo_ms, 50)), 3) if slo_ms else None
        ),
        "request_ms_p99": (
            round(float(np.percentile(slo_ms, 99)), 3) if slo_ms else None
        ),
        "slo_samples": len(slo_ms),
        # dispatcher cache + resident-reuse observability (ISSUE 6)
        "graph_cache": m["graph_cache"],
        "resident_delta_requests": sum(
            t["resident_delta_requests"] for t in m["tenants"].values()
        ),
    }


class _DeviceSimDispatcher:
    """A dispatcher whose 'device' is a calibrated sleep: ``dispatch``
    stamps the batch ready ``batch16_ms * width/16`` later and ``fetch``
    sleeps until then (releasing the GIL — the host is FREE during
    device compute, which is what a device-attached replica looks like
    and what a CPU-backend engine on this host cannot reproduce: XLA:CPU
    burns the same cores the scheduler runs on).  Calibrated from the
    REAL engine's measured batch-16 wall, so the sim's per-batch cost is
    this host's actual device cost — only its placement moves off-host.
    Drives the real pool/replica/routing/steal machinery end to end."""

    engine = None
    engine_tag = "serve+devsim"

    def __init__(self, batch16_ms: float):
        self.batch16_ms = float(batch16_ms)
        self.dispatched = 0

    def has_graph(self, key):
        return False

    def dispatch(self, batch, now=None):
        import time

        self.dispatched += 1
        ready_at = (
            time.perf_counter()
            + self.batch16_ms * len(batch) / 16.0 / 1e3
        )

        class _H:  # noqa: N801 - tiny local handle
            requests = list(batch)
            dispatched_at = now if now is not None else 0.0

        _H.ready_at = ready_at
        return _H

    def fetch(self, handle):
        import time

        dt = handle.ready_at - time.perf_counter()
        if dt > 0:
            time.sleep(dt)

        class _R:  # noqa: N801 - minimal EngineResult stand-in
            ranked = [{"component": "sim", "score": 1.0}]
            engine = "serve+devsim"

        return [_R() for _ in handle.requests]


def gateway_metrics(engine, n_services: int = 256) -> dict:
    """``gateway`` (ISSUE 9): what the wire front door COSTS and whether
    its backpressure is honest.

    - **wire vs in-process**: closed-loop p50/p99 request latency at
      concurrency 16 through the loopback HTTP gateway vs the same load
      through the in-process ``ServeClient`` (same started loop, same
      graph) — the delta is pure wire overhead (JSON codec + TCP + HTTP
      framing), since both paths ride the identical scheduler;
    - **shed-rate at 2× capacity**: a deliberately slow device sim
      behind a small queue, blasted with twice its admission capacity —
      every response must be terminal and the overload must surface as
      429 (queue_full), not hangs;
    - **canary replay throughput**: one sampled+minted canary round and
      the rate its recording replays back through the real engine (the
      cost of the continuous regression stream).
    """
    import tempfile
    import threading
    import time

    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.config import ServeConfig
    from rca_tpu.gateway import GatewayClient, GatewayServer
    from rca_tpu.serve import ServeClient, ServeLoop

    case = synthetic_cascade_arrays(n_services, n_roots=1, seed=0)
    rng = np.random.default_rng(0)
    feats = [
        np.clip(case.features + rng.uniform(
            0, 0.05, case.features.shape
        ).astype(np.float32), 0, 1)
        for _ in range(16)
    ]

    def closed_loop(fire, concurrency=16, per_worker=3):
        samples = []
        lock = threading.Lock()

        def worker(w):
            for j in range(per_worker):
                t0 = time.perf_counter()
                fire(feats[(w + j) % len(feats)], f"w{w}")
                dt = (time.perf_counter() - t0) * 1e3
                with lock:
                    samples.append(dt)

        threads = [
            threading.Thread(target=worker, args=(w,))
            for w in range(concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return samples

    loop = ServeLoop(engine=engine).start()
    try:
        gw = GatewayServer(loop, port=0)
        gw.start()
        try:
            wire_client = GatewayClient(gw.host, gw.port,
                                        timeout_s=300.0)
            inproc = ServeClient(loop)

            def fire_wire(f, tenant):
                code, body, _ = wire_client.analyze(
                    f, case.dep_src, case.dep_dst, tenant=tenant, k=5,
                )
                assert code == 200, body

            def fire_inproc(f, tenant):
                resp = inproc.analyze(
                    f, case.dep_src, case.dep_dst, tenant=tenant, k=5,
                )
                assert resp.ok, resp.status

            # warm the batched executables first: a concurrency-16
            # closed loop coalesces at varying widths, and each pow2
            # pad width compiles once (~0.5 s on CPU) — warmup runs the
            # SAME load shape untimed so both timed legs measure steady
            # state, not compile roulette
            closed_loop(fire_inproc)
            fire_wire(feats[0], "warmup")
            closed_loop(fire_wire)
            wire_ms = closed_loop(fire_wire)
            inproc_ms = closed_loop(fire_inproc)
        finally:
            gw.close()
    finally:
        loop.stop()

    def pct(xs, q):
        return round(float(np.percentile(xs, q)), 3)

    # -- shed-rate correctness at 2x admission capacity ----------------------
    # capacity = what the plane can HOLD without rejecting: the queue
    # cap + the batcher's staging window (4 batches ahead) + one batch
    # in flight; a near-simultaneous blast of 2x that must surface the
    # excess as 429s (the slow device sim keeps drain out of the race)
    cap, max_batch = 8, 4
    capacity = cap + max_batch * 4 + max_batch
    overload_total = 2 * capacity
    slow = _DeviceSimDispatcher(batch16_ms=800.0)
    shed_loop = ServeLoop(
        dispatcher=slow,
        config=ServeConfig(queue_cap=cap, max_batch=max_batch,
                           max_wait_us=0),
    ).start()
    outcomes = []
    out_lock = threading.Lock()
    try:
        shed_gw = GatewayServer(shed_loop, port=0)
        shed_gw.start()
        try:
            shed_client = GatewayClient(shed_gw.host, shed_gw.port,
                                        timeout_s=300.0)

            def overload_worker(w):
                code, body, _ = shed_client.analyze(
                    feats[w % len(feats)], case.dep_src, case.dep_dst,
                    tenant=f"o{w % 4}", k=5,
                )
                with out_lock:
                    outcomes.append(code)

            threads = [
                threading.Thread(target=overload_worker, args=(w,))
                for w in range(overload_total)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            shed_gw.close()
    finally:
        shed_loop.stop()
    n429 = sum(1 for c in outcomes if c == 429)
    n200 = sum(1 for c in outcomes if c == 200)

    # -- canary replay throughput --------------------------------------------
    from rca_tpu.gateway import run_canary
    from rca_tpu.replay import replay_stream

    tmp = tempfile.mkdtemp(prefix="rca_gateway_bench_")
    canary_ticks = 12
    t0 = time.perf_counter()
    canary = run_canary(tmp, rounds=1, ticks=canary_ticks, services=50,
                        seed=0, mode="stream")
    canary_wall_s = time.perf_counter() - t0
    rec_path = canary["recordings"][0]["recording"]
    t0 = time.perf_counter()
    rep = replay_stream(rec_path)
    replay_s = time.perf_counter() - t0

    return {
        "wire_request_ms_p50": pct(wire_ms, 50),
        "wire_request_ms_p99": pct(wire_ms, 99),
        "inprocess_request_ms_p50": pct(inproc_ms, 50),
        "inprocess_request_ms_p99": pct(inproc_ms, 99),
        "wire_overhead_ms_p50": round(
            pct(wire_ms, 50) - pct(inproc_ms, 50), 3
        ),
        "concurrency": 16,
        # wire overhead is JSON codec + HTTP framing, CPU-bound: on a
        # single-core container 16 concurrent ~75 KB bodies serialize
        # behind one core (serial wire overhead is <1 ms) — same
        # honest-host caveat as serve_pool's real-engine leg
        "host_cores": os.cpu_count(),
        # overload leg: 2x capacity must map to 429s, never hangs
        "overload_requests": overload_total,
        "overload_capacity": capacity,
        "overload_queue_cap": cap,
        "overload_429": n429,
        "overload_200": n200,
        "overload_all_terminal": len(outcomes) == overload_total,
        "overload_backpressure_engaged": n429 > 0,
        "shed_rate_429": round(n429 / overload_total, 3),
        # the continuous regression stream's cost
        "canary_sample_mint_replay_s": round(canary_wall_s, 3),
        "canary_parity_ok": bool(canary["ok"]),
        "canary_replay_ticks_per_sec": round(
            rep["ticks_replayed"] / max(replay_s, 1e-9), 1
        ),
    }


def serve_pool_metrics(
    concurrency: int = 64,
    n_requests: int = 192,
    replicas: int = 4,
    seed: int = 0,
) -> dict:
    """``serve_pool`` (ISSUE 8): the multi-replica serving plane vs the
    single-replica scheduler on the SAME host — aggregate
    investigations/s at ``concurrency`` concurrent submitters over a
    multi-bucket tenant mix (8 distinct service graphs, so the pool's
    shape-bucket routing actually has buckets to spread), plus a
    replica-kill leg: replica 0 dies mid-run and the work-stealing
    rebalance must answer-or-shed EVERYTHING, with the recovery wall
    (kill → last response) reported.  A sampled bit-parity check pins
    pool responses to solo analyses.

    Two throughput legs, both through the identical pool machinery:

    - ``real_engine``: replicas backed by XLA:CPU engines.  On a
      multi-core host this shows the replica scaling directly; on a
      single-core host (this container: see ``host_cores``) compute is
      work-conserving and the honest expectation is ~1.0x — the same
      caveat family as PERF.md round-7's tunnel note;
    - ``device_attached_sim``: replicas whose device cost is a sleep
      CALIBRATED to the real engine's measured batch-16 wall — the host
      is free during device compute, which is the TPU-host shape.  This
      is the headline ``pool_speedup``: what the serving plane itself
      buys once compute lives on accelerators.

    Run via ``python bench.py --serve-pool-only`` inside an 8-virtual-
    device host (the main bench shells out exactly that, mirroring the
    sharded-tick dry run) so replicas genuinely own device groups."""
    import threading
    import time

    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.config import ServeConfig, parse_replica_mix
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.serve import ServePool, build_replica_engines
    from rca_tpu.serve.client import ServeClient

    # 8 distinct shape buckets (different edge digests, SAME size tier so
    # the warmup below can cover every executable) — the tenant mix a
    # pool is for: one hot bucket would pin to one replica and measure
    # stickiness, not scaling
    cases = [
        synthetic_cascade_arrays(512, n_roots=1, seed=seed + i)
        for i in range(8)
    ]
    rng = np.random.default_rng(seed)
    plan = []
    for i in range(n_requests):
        case = cases[i % len(cases)]
        feats = np.clip(
            case.features + rng.uniform(
                0, 0.05, case.features.shape
            ).astype(np.float32),
            0, 1,
        )
        plan.append((case, feats))

    solo_engine = GraphEngine()

    def run(nrep: int, kill: bool = False,
            sim_ms: float = 0.0) -> dict:
        cfg = ServeConfig(
            replicas=nrep, max_batch=16, max_wait_us=2000,
            queue_cap=max(256, n_requests),
        )
        if sim_ms > 0:
            pool = ServePool(
                dispatchers=[
                    _DeviceSimDispatcher(sim_ms) for _ in range(nrep)
                ],
                config=cfg,
            )
        else:
            triples = build_replica_engines(parse_replica_mix("", nrep))
            pool = ServePool(engines=triples, config=cfg)
        responses = [None] * n_requests
        kill_at = {"t": None}
        # warm every (bucket, pow2 width) executable on every replica's
        # device OUTSIDE the timed window — jit caches per device, and a
        # cold compile inside the run would time XLA, not serving
        from rca_tpu.serve import ServeRequest

        if sim_ms <= 0:
            for rep in pool.replicas:
                for case in cases:
                    w = 1
                    while w <= 16:
                        batch = [
                            ServeRequest(
                                tenant="warm", features=case.features,
                                dep_src=case.dep_src,
                                dep_dst=case.dep_dst,
                                names=case.names, k=5,
                            )
                            for _ in range(w)
                        ]
                        with rep._device_ctx():
                            rep.dispatcher.fetch(
                                rep.dispatcher.dispatch(batch)
                            )
                        w *= 2
        with pool:
            client = ServeClient(pool)
            t0 = time.perf_counter()

            def submitter(worker: int) -> None:
                pending = []
                for i in range(worker, n_requests, concurrency):
                    case, feats = plan[i]
                    if kill and worker == 0 and i >= n_requests // 3:
                        if kill_at["t"] is None:
                            kill_at["t"] = time.perf_counter()
                            pool.replicas[0].kill()
                    pending.append((i, client.submit(
                        feats, case.dep_src, case.dep_dst,
                        names=case.names, tenant=f"t{worker % 8}", k=5,
                    )))
                for i, req in pending:
                    responses[i] = req.result(600.0)

            threads = [
                threading.Thread(target=submitter, args=(w,))
                for w in range(concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall_s = time.perf_counter() - t0
        by_status = {}
        for resp in responses:
            key = resp.status if resp is not None else "unresolved"
            by_status[key] = by_status.get(key, 0) + 1
        m = pool.metrics.summary()
        return {
            "wall_s": wall_s,
            "by_status": by_status,
            "answered_or_shed": all(
                r is not None and r.status in ("ok", "shed", "degraded")
                for r in responses
            ),
            "investigations_per_sec": round(
                by_status.get("ok", 0) / max(wall_s, 1e-9), 1
            ),
            "recovery_ms": (
                round((time.perf_counter() - kill_at["t"]) * 1e3, 1)
                if kill_at["t"] is not None else None
            ),
            "steals": m.get("steals_total", 0),
            "double_completions": pool.sink.double_completions,
            "occupancy": {
                rid: {
                    "requests": rec["requests"],
                    "occupancy_p50": rec["occupancy_p50"],
                }
                for rid, rec in m.get("replicas", {}).items()
            },
            "responses": responses,
        }

    # real-engine legs + the kill/recovery leg
    solo = run(1)
    pooled = run(replicas)
    killed = run(replicas, kill=True)

    # calibrate the device-attached sim from the REAL engine: one
    # batch-16 dispatch+fetch wall on the warmed hot bucket
    from rca_tpu.serve import BatchDispatcher, ServeRequest

    disp = BatchDispatcher(solo_engine)
    reqs16 = [
        ServeRequest(
            tenant="cal", features=cases[0].features,
            dep_src=cases[0].dep_src, dep_dst=cases[0].dep_dst,
            names=cases[0].names, k=5,
        )
        for _ in range(16)
    ]
    disp.fetch(disp.dispatch(reqs16))  # warm
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        disp.fetch(disp.dispatch(reqs16))
    batch16_ms = (time.perf_counter() - t0) / reps * 1e3

    sim_solo = run(1, sim_ms=batch16_ms)
    sim_pool = run(replicas, sim_ms=batch16_ms)

    # sampled bit parity: pool responses vs solo analyses
    parity_ok = True
    for i in range(0, n_requests, max(1, n_requests // 8)):
        resp = pooled["responses"][i]
        if resp is None or not resp.ok:
            continue
        case, feats = plan[i]
        ref = solo_engine.analyze_arrays(
            feats, case.dep_src, case.dep_dst, case.names, k=5,
        )
        if resp.ranked != ref.ranked or not np.array_equal(
            resp.result.score, ref.score
        ):
            parity_ok = False

    solo_ips = solo["investigations_per_sec"]
    pool_ips = pooled["investigations_per_sec"]
    sim_solo_ips = sim_solo["investigations_per_sec"]
    sim_pool_ips = sim_pool["investigations_per_sec"]
    return {
        "concurrency": concurrency,
        "requests": n_requests,
        "replicas": replicas,
        "host_cores": len(os.sched_getaffinity(0)),
        # headline: the serving plane's own scaling with device-attached
        # compute (calibrated sleep device; see docstring) — what N
        # replicas buy when XLA:CPU is not stealing the scheduler's core
        "pool_speedup": round(
            sim_pool_ips / max(sim_solo_ips, 1e-9), 2
        ),
        "device_attached_sim": {
            "calibrated_batch16_ms": round(batch16_ms, 1),
            "solo_investigations_per_sec": sim_solo_ips,
            "pool_investigations_per_sec": sim_pool_ips,
            "occupancy_per_replica": sim_pool["occupancy"],
        },
        "real_engine": {
            "solo_investigations_per_sec": solo_ips,
            "pool_investigations_per_sec": pool_ips,
            # work-conserving on a single-core host (see host_cores):
            # XLA:CPU compute shares the scheduler's core, so ~1.0 is
            # the honest ceiling there; multi-core hosts show the
            # replica scaling directly
            "pool_speedup": round(pool_ips / max(solo_ips, 1e-9), 2),
            "occupancy_per_replica": pooled["occupancy"],
        },
        "pool_vs_solo_parity_ok": bool(parity_ok),
        "replica_kill": {
            "recovery_ms": killed["recovery_ms"],
            "answered_or_shed": killed["answered_or_shed"],
            "by_status": killed["by_status"],
            "steals": killed["steals"],
            "double_completions": killed["double_completions"],
            "investigations_per_sec": killed["investigations_per_sec"],
        },
    }


def serve_federation_metrics(
    workers: int = 3,
    concurrency: int = 8,
    n_requests: int = 48,
    services: int = 256,
    seed: int = 0,
) -> dict:
    """``serve_federation`` (ISSUE 15): the cross-process serving plane
    — ``workers`` localhost worker PROCESSES behind one control plane —
    vs the single-process ServeLoop on the same host, closed loop at
    ``concurrency``.  Three legs:

    - **throughput**: request p50/p99 over a multi-bucket mix, single
      process vs federation (the federation pays one wire hop +
      JSON codec per request; on a 1-core host the worker processes
      also contend for the CPU — ``host_cores`` is printed so the
      number reads honestly);
    - **kill**: SIGKILL one worker mid-wave — asserts every request
      terminal, ``double_completions == 0``, and reports
      ``recovery_ms`` (kill → all terminal);
    - **liveness**: the lease-expiry detection lag observed for the
      killed worker (EOF path) and the configured TTL.
    """
    import threading
    import time

    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.serve.federation import FederationPlane
    from rca_tpu.serve.loop import ServeLoop
    from rca_tpu.serve.request import ServeRequest

    cases = [
        synthetic_cascade_arrays(services, n_roots=1, seed=seed + i)
        for i in range(4)
    ]
    rng = np.random.default_rng(seed)
    plan = []
    for i in range(n_requests):
        case = cases[i % len(cases)]
        feats = np.clip(
            case.features + rng.uniform(
                0, 0.05, case.features.shape
            ).astype(np.float32),
            0, 1,
        )
        plan.append((case, feats))

    def closed_loop(submit, kill_at=None, kill_fn=None):
        """Closed-loop wave: ``concurrency`` submitters each walk their
        slice serially.  Returns (wall_s, per-request ms, responses,
        kill timestamp)."""
        latencies = [0.0] * len(plan)
        responses = [None] * len(plan)
        killed_at = [None]
        lock = threading.Lock()
        done_count = [0]

        def worker_thread(w):
            for i in range(w, len(plan), concurrency):
                case, feats = plan[i]
                with lock:
                    n = done_count[0]
                    if (kill_at is not None and n >= kill_at
                            and killed_at[0] is None):
                        killed_at[0] = time.perf_counter()
                        kill_fn()
                t0 = time.perf_counter()
                req = ServeRequest(
                    tenant=f"bench-{w % 4}", features=feats,
                    dep_src=case.dep_src, dep_dst=case.dep_dst,
                    names=case.names, k=3,
                )
                submit(req)
                responses[i] = req.result(300.0)
                latencies[i] = (time.perf_counter() - t0) * 1e3
                with lock:
                    done_count[0] += 1

        threads = [
            threading.Thread(target=worker_thread, args=(w,), daemon=True)
            for w in range(concurrency)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return (time.perf_counter() - t0, latencies, responses,
                killed_at[0])

    def pcts(lat):
        s = sorted(lat)
        return (round(s[len(s) // 2], 2),
                round(s[int(len(s) * 0.99) - 1], 2))

    # single-process baseline (same plan, same closed loop)
    solo_loop = ServeLoop(engine=GraphEngine())
    with solo_loop:
        # warm every bucket's executable out of the measurement
        for case, feats in plan[:len(cases)]:
            req = ServeRequest(tenant="warm", features=feats,
                               dep_src=case.dep_src, dep_dst=case.dep_dst,
                               names=case.names, k=3)
            solo_loop.submit(req)
            req.result(300.0)
        _, solo_lat, solo_resps, _ = closed_loop(solo_loop.submit)
    solo_p50, solo_p99 = pcts(solo_lat)
    assert all(r is not None and r.ok for r in solo_resps)

    # federation throughput leg
    plane = FederationPlane(workers=workers, heartbeat_s=0.2)
    with plane:
        ready = plane.wait_ready(workers, timeout_s=120.0)
        assert ready, f"federation bench: workers failed to join"
        startup_s = None
        for case, feats in plan[:len(cases)]:
            req = ServeRequest(tenant="warm", features=feats,
                               dep_src=case.dep_src, dep_dst=case.dep_dst,
                               names=case.names, k=3)
            plane.submit(req)
            req.result(300.0)
        wall_s, fed_lat, fed_resps, _ = closed_loop(plane.submit)
        assert all(r is not None for r in fed_resps)
        fed_ok = sum(1 for r in fed_resps if r.ok)
        fed_double = plane.sink.double_completions
    fed_p50, fed_p99 = pcts(fed_lat)

    # kill leg: fresh fleet, SIGKILL one worker mid-wave
    plane2 = FederationPlane(workers=workers, heartbeat_s=0.2)
    with plane2:
        assert plane2.wait_ready(workers, timeout_s=120.0)
        for case, feats in plan[:len(cases)]:
            req = ServeRequest(tenant="warm", features=feats,
                               dep_src=case.dep_src, dep_dst=case.dep_dst,
                               names=case.names, k=3)
            plane2.submit(req)
            req.result(300.0)

        def kill_one():
            live = plane2.live_workers()
            if live:
                plane2.kill_worker(live[0])

        t_wave0 = time.perf_counter()
        _, kill_lat, kill_resps, t_kill = closed_loop(
            plane2.submit, kill_at=n_requests // 3, kill_fn=kill_one,
        )
        t_all_terminal = time.perf_counter()
        # the federation kill contract, ASSERTED in the bench itself:
        # nothing hung, nothing double-completed
        assert all(r is not None for r in kill_resps), \
            "federation kill leg: a request never completed"
        assert plane2.sink.double_completions == 0, \
            "federation kill leg: double completion"
        kill_status: dict = {}
        for r in kill_resps:
            kill_status[r.status] = kill_status.get(r.status, 0) + 1
        detect = [
            e.get("detect_lag_ms") for e in plane2.events
            if e["event"] == "worker_down"
        ]
        stale2 = plane2.stale_responses
        ttl_s = plane2.leases.ttl_s

    return {
        "workers": workers,
        "concurrency": concurrency,
        "requests": n_requests,
        "host_cores": len(os.sched_getaffinity(0)),
        "solo_request_ms_p50": solo_p50,
        "solo_request_ms_p99": solo_p99,
        "request_ms_p50": fed_p50,
        "request_ms_p99": fed_p99,
        "wire_hop_overhead_ms_p50": round(fed_p50 - solo_p50, 2),
        "throughput_rps": round(n_requests / max(wall_s, 1e-9), 1),
        "ok_responses": fed_ok,
        "double_completions": fed_double,
        "kill_leg": {
            "recovery_ms": round(
                (t_all_terminal - t_kill) * 1e3, 1
            ) if t_kill is not None else None,
            "by_status": kill_status,
            "all_terminal": True,      # asserted above
            "double_completions": 0,   # asserted above
            "stale_responses": stale2,
        },
        # the kill-leg recovery wall doubles as the guard metric
        "recovery_ms": round(
            (t_all_terminal - t_kill) * 1e3, 1
        ) if t_kill is not None else None,
        "lease": {
            "ttl_s": ttl_s,
            "detect_lag_ms": [
                round(d, 1) for d in detect if d is not None
            ],
        },
    }


def serve_autoscale_metrics(seed: int = 0) -> dict:
    """``serve_autoscale`` (ISSUE 16): the elastic fleet's load-ramp
    soak as a measurement — thread-mode workers walk 2→8→2 under
    continuous closed-loop traffic with the exactly-once and
    all-terminal contracts ASSERTED in-run.  Reports the request
    p50/p99 THROUGH both transitions (the elastic tax a fixed fleet
    never pays), the windowed queue p99 right after the up-ramp,
    ramp walls, the controller's per-sweep decision latency, and the
    shape-aware placement hit rate (0.0 on hosts whose kernel registry
    has no autotuned timings to advertise — rendezvous fallback)."""
    from rca_tpu.serve.autoscale import run_scale_ramp_soak

    out = run_scale_ramp_soak(seed=seed, min_workers=2, max_workers=8)
    assert out["all_terminal"], "autoscale soak: a request never completed"
    assert out["double_completions"] == 0, "autoscale soak: double completion"
    return {
        "ok": out["ok"],
        "min_workers": out["min_workers"],
        "max_workers": out["max_workers"],
        "requests": out["requests"],
        "host_cores": len(os.sched_getaffinity(0)),
        "ramp_request_ms_p50": out["request_ms_p50"],
        "ramp_request_ms_p99": out["request_ms_p99"],
        "queue_ms_p99_after_up": out["queue_ms_p99_after_up"],
        "ramp_up_s": out["ramp_up_s"],
        "ramp_down_s": out["ramp_down_s"],
        "scale_ups": out["scale_ups"],
        "scale_downs": out["scale_downs"],
        "scale_decision_ms_p50": out["scale_decision_ms_p50"],
        "placement_hit_rate": out["placement_hit_rate"],
        "stale_responses": out["stale_responses"],
        "by_status": out["by_status"],
    }


def main(skip_accuracy: bool = False, with_chaos: bool = False,
         guard: bool = False) -> int:
    """Stdout-hygiene wrapper: the whole measurement body runs with
    ``sys.stdout`` pointed at stderr, so any chatter a stage emits cannot
    precede the result line — the JSON prints to the REAL stdout as its
    sole line (the harness parses exactly that)."""
    real_stdout = sys.stdout
    sys.stdout = sys.stderr
    try:
        return _bench_main(real_stdout, skip_accuracy, with_chaos, guard)
    finally:
        sys.stdout = real_stdout


def _bench_main(real_stdout, skip_accuracy: bool = False,
                with_chaos: bool = False, guard: bool = False) -> int:
    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import GraphEngine, make_engine

    n_services = 2000
    n_roots = 3
    case = synthetic_cascade_arrays(n_services, n_roots=n_roots, seed=0)
    # the headline metric runs whatever engine the analyze boundary would
    # pick HERE (single-device on the one-chip bench host; sharded when
    # RCA_SHARD/multi-chip) and records which one ran; the layout/kernel
    # micro-measurements below drive the dense engine's internals directly
    headline_engine = make_engine()
    engine = (
        headline_engine
        if isinstance(headline_engine, GraphEngine) else GraphEngine()
    )
    result = headline_engine.analyze_case(case, k=5, timed=True)

    truth = {case.names[r] for r in case.roots.tolist()}
    top1_hit = result.ranked[0]["component"] in truth
    topk = set(result.top_components(n_roots))
    all_roots_topk = truth <= topk

    # hit@1 across seeds for a robust accuracy figure (single-root cases)
    hits = 0
    trials = 20
    for seed in range(trials):
        c = synthetic_cascade_arrays(500, n_roots=1, seed=seed)
        r = engine.analyze_case(c, k=1)
        hits += r.ranked[0]["component"] == c.names[c.roots[0]]

    # scale extra: 50k-service single-chip inference (BASELINE.md 50k row).
    # Per-inference device time amortized over R in-executable repetitions
    # (per-dispatch host overhead excluded — it is environment transport, not
    # graph inference; the 2k headline metric keeps dispatch included).
    import functools
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rca_tpu.engine.propagate import propagate

    aw, hw = engine.params.weight_arrays()
    p = engine.params
    prop = functools.partial(
        propagate, anomaly_w=aw, hard_w=hw, steps=p.steps, decay=p.decay,
        explain_strength=p.explain_strength, impact_bonus=p.impact_bonus,
    )

    # the per-sync round trip (dispatch + fetch of a tiny buffer): this is
    # transport, not inference — measured once and reported for context;
    # the amortized numbers below cancel it via their marginal form
    @jax.jit
    def _triv(x, s):
        return x * s

    xt = jnp.ones((8,))
    jax.device_get(_triv(xt, jnp.float32(1.0)))
    floors = []
    for j in range(10):
        t0 = time.perf_counter()
        jax.device_get(_triv(xt, jnp.float32(j + 2.0)))
        floors.append((time.perf_counter() - t0) * 1e3)
    sync_floor_ms = float(np.median(floors))

    def amort_min_ms(make_many, args, reps_in_jit, outer=5):
        """Shared amortized-timing scaffold, MARGINAL form: time a jitted
        R-rep loop and a 2R-rep loop (min over ``outer`` dispatches each;
        transient contention only inflates) and report (t_2R - t_R) / R —
        the per-sync transport floor cancels exactly, leaving pure device
        compute per rep, immune to the floor's run-to-run jitter.
        ``make_many`` receives the rep count so the loop length and the
        divisor cannot drift, and its function must take a trailing ``salt``
        scalar folded into the computation — every dispatch carries a fresh
        salt so no transport layer can serve a cached result for a repeated
        identical call.  Syncs by FETCHING a 4-element slice (see module
        docstring) — never by block_until_ready."""

        def min_total(reps):
            many = make_many(reps)
            jax.device_get(many(*args, jnp.float32(1e-7))[:4])
            outs = []
            for j in range(outer):
                salt = jnp.float32((j + 2) * 1e-7)
                t0 = time.perf_counter()
                jax.device_get(many(*args, salt)[:4])
                outs.append((time.perf_counter() - t0) * 1e3)
            return float(np.min(outs))

        reps = reps_in_jit
        for _ in range(3):
            t_r = min_total(reps)
            t_2r = min_total(2 * reps)
            if t_2r > t_r:
                return (t_2r - t_r) / reps
            # marginal vanished under floor jitter: quadruple the work so
            # the compute term dominates, instead of reporting a fake 0.0
            reps *= 4
        return None  # unresolvable — report honestly as unmeasured

    big = synthetic_cascade_arrays(50000, n_roots=5, seed=0)
    rb = engine.analyze_arrays(big.features, big.dep_src, big.dep_dst, k=5)
    big_top1 = int(np.argmax(rb.score)) in set(big.roots.tolist())

    big_n = big.features.shape[0]
    bf, bs, bd = engine._pad(big.features, big.dep_src, big.dep_dst)
    bfj, bsj, bdj = jnp.asarray(bf), jnp.asarray(bs), jnp.asarray(bd)

    from rca_tpu.engine.runner import up_ell_for

    def make_many_prop_for(n_live, prop_fn, up_ell=None,
                           down_seg=None, up_seg=None):
        def make_many(reps):
            @jax.jit
            def many(f, s, d, salt):
                def body(i, acc):
                    # scale features per rep so XLA cannot hoist the body
                    score = prop_fn(
                        f * (1.0 + salt + i * 1e-7), s, d, n_live=n_live,
                        up_ell=up_ell, down_seg=down_seg, up_seg=up_seg,
                    )[4]
                    return acc + score
                return jax.lax.fori_loop(0, reps, body, jnp.zeros(f.shape[0]))
            return many
        return make_many

    # measure the engine's REAL layout: segscan when engaged for the tier
    # (round 4 — the 50k default), hybrid up-table otherwise
    from rca_tpu.engine.segscan import seg_layouts_for

    big_down_seg, big_up_seg = seg_layouts_for(
        bf.shape[0], len(bs), big.dep_src, big.dep_dst
    )
    big_up_ell = (
        None if big_up_seg is not None
        else up_ell_for(bf.shape[0], big.dep_src, big.dep_dst)
    )
    big_ms = amort_min_ms(
        make_many_prop_for(big_n, prop, big_up_ell, big_down_seg, big_up_seg),
        (bfj, bsj, bdj), reps_in_jit=10,
    )

    # batched multi-hypothesis scoring (BASELINE.md 10k streaming row):
    # 16 perturbed feature sets over the 2k graph, one vmapped executable
    B = 16
    f, s, d = engine._pad(case.features, case.dep_src, case.dep_dst)
    # the engine's REAL 2k layout (segscan when engaged, else hybrid)
    ds_2k, us_2k = seg_layouts_for(f.shape[0], len(s), case.dep_src,
                                   case.dep_dst)
    up_ell_2k = (
        None if us_2k is not None
        else up_ell_for(f.shape[0], case.dep_src, case.dep_dst)
    )
    rng = np.random.default_rng(0)
    batch = np.clip(
        f[None].repeat(B, 0)
        + rng.uniform(0, 0.02, (B, *f.shape)).astype(np.float32),
        0, 1,
    )

    @jax.jit
    def batched(fb, s, d):
        return jax.vmap(
            lambda f: prop(f, s, d, n_live=n_services, up_ell=up_ell_2k,
                           down_seg=ds_2k, up_seg=us_2k)[4]
        )(fb)

    fb, sj, dj = jnp.asarray(batch), jnp.asarray(s), jnp.asarray(d)
    jax.device_get(batched(fb, sj, dj))
    reps = []
    for _ in range(10):
        t0 = time.perf_counter()
        jax.device_get(batched(fb, sj, dj))
        reps.append((time.perf_counter() - t0) * 1e3)
    batch_ms = float(np.median(reps))

    # marginal device cost per ADDED hypothesis (round 5, VERDICT r4
    # item 2): measured with the SAME in-jit marginal-rep methodology as
    # device_compute_ms_2k, per batch width — round 4 differenced dispatch
    # walltimes through a tunnel with a ~134 ms floor and multi-ms jitter,
    # which published a figure 20x off PERF.md's in-jit number.  Here each
    # width's PURE DEVICE time per batch dispatch comes from the
    # floor-cancelling (t_2R - t_R)/R form; the per-hypothesis marginal is
    # their difference over the added width.  Repeated 3x for jitter bars.
    def batch_device_ms(width, reps):
        fbw = jnp.asarray(batch[:1].repeat(width, 0))

        def make_many(reps_):
            @jax.jit
            def many(fb_, s_, d_, salt):
                def body(i, acc):
                    scores = jax.vmap(
                        lambda f: prop(
                            f * (1.0 + salt + i * 1e-7), s_, d_,
                            n_live=n_services, up_ell=up_ell_2k,
                            down_seg=ds_2k, up_seg=us_2k,
                        )[4]
                    )(fb_)
                    return acc + scores.sum(0)
                return jax.lax.fori_loop(
                    0, reps_, body, jnp.zeros(fb_.shape[1])
                )
            return many

        return amort_min_ms(make_many, (fbw, sj, dj), reps_in_jit=reps)

    _marginals = []
    for _ in range(3):
        t1 = batch_device_ms(1, 32)
        t64 = batch_device_ms(64, 4)
        if t1 is not None and t64 is not None:
            _marginals.append((t64 - t1) / 63.0)
    batch_marginal_ms = float(np.median(_marginals)) if _marginals else None
    batch_marginal_jitter_ms = (
        float(np.max(_marginals) - np.min(_marginals)) if _marginals else None
    )
    # a marginal BELOW the run-to-run jitter bound is noise, not a
    # measurement (BENCH_r05 published -0.0048 ms): per the PERF.md
    # "never print 0" rule it reports null, with the jitter bound kept
    # alongside as the honest resolution limit
    if (batch_marginal_ms is not None
            and batch_marginal_jitter_ms is not None
            and batch_marginal_ms < batch_marginal_jitter_ms):
        batch_marginal_ms = None

    # pure device compute per 2k inference, amortized over an in-jit loop
    # (the headline ``value`` is single-shot end-to-end and so includes one
    # sync_floor_ms of transport; this isolates the chip's share)
    f2, s2, d2 = jnp.asarray(f), jnp.asarray(s), jnp.asarray(d)
    device_2k_ms = amort_min_ms(
        make_many_prop_for(n_services, prop, up_ell_2k, ds_2k, us_2k),
        (f2, s2, d2), reps_in_jit=64,
    )

    # -- Pallas proof (VERDICT round-1 item 6): record whether the fused
    # noisy-OR kernel compiles on THIS backend and its amortized timing vs
    # the XLA expression at 50k scale.  (Measured wash on v5e — see
    # rca_tpu/engine/pallas_kernels.py docstring — hence opt-in.)
    from rca_tpu.config import RCAConfig, bucket_for
    from rca_tpu.engine.pallas_kernels import (
        noisy_or_pair_pallas,
        noisy_or_pair_xla,
        pallas_enabled,
        pallas_supported,
    )
    from rca_tpu.engine.registry import engaged_kernel

    pallas_ok = pallas_supported()
    aw_j, hw_j = jnp.asarray(aw), jnp.asarray(hw)
    ft = bfj.T  # kernel reads channel-major; bfj is the padded 50k matrix

    def nor_amort(fn, arg):
        def make_many(reps):
            @jax.jit
            def many(x, salt):
                def body(i, acc):
                    # 1e-7 stays above float32 half-ULP of 1.0, so every
                    # rep's input really differs and XLA cannot hoist
                    a, h = fn(x * (1.0 + salt + i * 1e-7), aw_j, hw_j)
                    return acc + a + h
                return jax.lax.fori_loop(0, reps, body, jnp.zeros(bfj.shape[0]))
            return many
        # high rep count: a single noisy-OR pass is ~20 us, so the pair must
        # be amortized far below the sync floor to be resolvable
        return amort_min_ms(make_many, (arg,), reps_in_jit=500)

    xla_nor_ms = nor_amort(noisy_or_pair_xla, bfj)
    pallas_nor_ms = nor_amort(noisy_or_pair_pallas, ft) if pallas_ok else None

    # -- streaming: 10k-service 1 Hz session (BASELINE.md row 4).  Device-
    # resident feature buffer; each tick flushes ~1% of services as a
    # donated-argument row scatter then reruns the cached executable.
    from rca_tpu.engine.streaming import StreamingSession
    from rca_tpu.obslog.profiling import PhaseStats

    sk = synthetic_cascade_arrays(10_000, n_roots=3, seed=1)

    def make_10k_session():
        s = StreamingSession(
            [f"svc-{i:05d}" for i in range(sk.n)], sk.dep_src, sk.dep_dst,
            num_features=sk.features.shape[1], k=5,
        )
        s.set_all(sk.features)
        s.tick()  # warm the propagation executable
        # warm the 128-row scatter tier too: no measured tick pays a compile
        s.update_many({i: sk.features[i] for i in range(100)})
        s.tick()
        return s

    # the SAME seeded delta sequence drives the serial and pipelined
    # loops, so their per-tick states — and rankings — are comparable
    srng = np.random.default_rng(2)
    delta_seq = []
    for _ in range(20):
        delta_seq.append({
            int(i): np.clip(
                sk.features[i]
                + srng.uniform(-0.05, 0.05, sk.features.shape[1]), 0, 1
            ).astype(np.float32)
            for i in srng.integers(0, sk.n, 100)
        })

    sess = make_10k_session()
    serial_phases = PhaseStats()
    tick_times = []
    serial_ranked = []
    for rows in delta_seq:
        sess.update_many(rows)
        out = sess.tick()
        tick_times.append(out["latency_ms"])
        serial_phases.record_tick(out)
        serial_ranked.append(out["ranked"])
    tick_ms_10k = float(np.median(tick_times))
    tick_upload_rows = int(out["upload_rows"])

    # pipelined twin (ISSUE 2 tentpole): dispatch tick N, stage tick N+1's
    # deltas, THEN fetch tick N — per-tick wall is what the overlap leaves,
    # not capture + RTT summed.  Fresh session (identical warmup) so both
    # loops start from the same device state; ranking parity is asserted.
    sess_p = make_10k_session()
    pipe_phases = PhaseStats()
    pipe_iter_times = []
    pipe_ranked = []
    prev = None
    for rows in delta_seq:
        t0 = time.perf_counter()
        with pipe_phases.phase("capture"):
            sess_p.update_many(rows)
        h = sess_p.dispatch()
        pipe_phases.record("dispatch", h.dispatch_ms)
        if prev is not None:
            out_p = sess_p.fetch(prev)
            pipe_phases.record("fetch", out_p["fetch_ms"])
            pipe_ranked.append(out_p["ranked"])
        prev = h
        pipe_iter_times.append((time.perf_counter() - t0) * 1e3)
    out_p = sess_p.fetch(prev)  # drain the last in-flight tick
    pipe_ranked.append(out_p["ranked"])
    # first iteration fetches nothing (pipeline fill) — excluded
    tick_ms_10k_pipelined = float(np.median(pipe_iter_times[1:]))
    pipeline_parity_ok = pipe_ranked == serial_ranked

    def phase_medians(ps):
        return {
            name: rec["median_ms"] for name, rec in ps.summary().items()
        }

    # -- live capture path at 10k (VERDICT r2 item 6): watch-driven quiet
    # polls vs full-sweep polls, HOST-side capture cost (capture_ms —
    # the device tick and its tunnel RTT are the same for both and are
    # already measured as tick_ms_10k above)
    from rca_tpu.cluster.generator import synthetic_cascade_world
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.engine import LiveStreamingSession

    lw = synthetic_cascade_world(10_000, n_roots=3, seed=1,
                                 namespace="live10k")
    lclient = MockClusterClient(lw)
    lsess = LiveStreamingSession(
        lclient, "live10k", k=5, topology_check_every=10_000,
    )
    lsess.poll()  # warm the tick executable
    quiet_caps = [lsess.poll()["capture_ms"] for _ in range(5)]
    # sweep sessions ride the columnar tables by default since ISSUE 10;
    # the dict twin below is the pre-columnar baseline measured in the
    # SAME run, with bit parity of the two extraction paths asserted on
    # this same world (a fast sweep that moved one bit measures nothing)
    sweep_sess = LiveStreamingSession(
        lclient, "live10k", k=5, use_watch=False,
        topology_check_every=10_000,
    )
    sweep_caps = [sweep_sess.poll()["capture_ms"] for _ in range(3)]
    sweep_sess_dict = LiveStreamingSession(
        lclient, "live10k", k=5, use_watch=False,
        topology_check_every=10_000, engine=sweep_sess.engine,
        use_columnar=False,
    )
    sweep_caps_dict = [
        sweep_sess_dict.poll()["capture_ms"] for _ in range(3)
    ]
    from rca_tpu.features.extract import extract_features as _exf

    _snap_c = ClusterSnapshot.capture(lclient, "live10k")
    _snap_d = ClusterSnapshot.capture(lclient, "live10k", columnar=False)
    _fs_c, _fs_d = _exf(_snap_c), _exf(_snap_d)
    columnar_parity_10k = (
        _snap_c.columnar is not None
        and _fs_c.pod_features.tobytes() == _fs_d.pod_features.tobytes()
        and _fs_c.service_features.tobytes()
        == _fs_d.service_features.tobytes()
    )
    assert columnar_parity_10k, "columnar-vs-dict parity FAILED at 10k"
    del _snap_c, _snap_d, _fs_c, _fs_d
    live_quiet_ms = float(np.median(quiet_caps))
    live_sweep_ms = float(np.median(sweep_caps))
    live_sweep_dict_ms = float(np.median(sweep_caps_dict))

    # forced feed expiry at 10k (VERDICT r3 item 6): trim the journal past
    # the session's cursor and measure the GRACEFUL recovery capture — one
    # pod re-list + value diff instead of the old full resync (which cost
    # the sweep figure above)
    old_cap = lw.journal_cap
    lw.journal_cap = 2
    for i in range(5):
        lw.touch("pod", "live10k", f"ghost-{i}")
    lw.journal_cap = old_cap
    rec = lsess.poll()
    live_recovery_ms = float(rec["capture_ms"])
    live_recovered = bool(rec.get("recovered"))

    # -- 50k sharded STREAMING dryrun tick (VERDICT r3 item 3): the
    # sp-sharded resident-buffer session validated at full scale on the
    # 8-device virtual CPU mesh in a subprocess (the bench host has one
    # chip).  A FUNCTIONAL number — CPU-mesh wall time per tick, proving
    # the 50k live path runs sharded — not a TPU perf figure.
    import subprocess

    _dryrun_src = (
        # config.update, not just the env var: a site hook may have
        # force-registered an accelerator plugin (axon) that the env var
        # alone does not override (same defense as tests/conftest.py)
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import json, numpy as np\n"
        "from rca_tpu.cluster.generator import synthetic_cascade_arrays\n"
        "from rca_tpu.engine import ShardedGraphEngine\n"
        "from rca_tpu.parallel.streaming import ShardedStreamingSession\n"
        "c = synthetic_cascade_arrays(50_000, n_roots=5, seed=0)\n"
        "s = ShardedStreamingSession([f's{i}' for i in range(c.n)],\n"
        "    c.dep_src, c.dep_dst, c.features.shape[1],\n"
        "    engine=ShardedGraphEngine(spec='sp=8'), k=5)\n"
        "s.set_all(c.features)\n"
        "s.tick()\n"  # compile + bulk upload
        "rng = np.random.default_rng(0)\n"
        "for i in rng.integers(0, c.n, 9):\n"
        "    s.update(int(i), np.clip(c.features[i] + 0.3, 0, 1))\n"
        "out = s.tick()\n"
        "top1 = out['ranked'][0]['component']\n"
        "hit = top1 in {f's{r}' for r in c.roots.tolist()}\n"
        # pipelined ticks over the same session (ISSUE 2): dispatch N,
        # stage N+1's deltas, fetch N — wall per tick with the fetch
        # overlapped, same dispatch/fetch split as the dense session
        "import time\n"
        "prev = None\n"
        "iters = 4\n"
        "t0 = time.perf_counter()\n"
        "for t in range(iters):\n"
        "    for i in rng.integers(0, c.n, 9):\n"
        "        s.update(int(i), np.clip(c.features[i] + 0.1 + t * 0.01,"
        " 0, 1))\n"
        "    h = s.dispatch()\n"
        "    if prev is not None:\n"
        "        s.fetch(prev)\n"
        "    prev = h\n"
        "s.fetch(prev)\n"
        "pipe_ms = (time.perf_counter() - t0) * 1e3 / iters\n"
        "print(json.dumps({'tick_ms': out['latency_ms'], 'top1_hit': hit,"
        " 'tick_ms_pipelined': pipe_ms}))\n"
    )
    try:
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(env.get("XLA_FLAGS", "")
                       + " --xla_force_host_platform_device_count=8").strip(),
        )
        proc = subprocess.run(
            [sys.executable, "-c", _dryrun_src], capture_output=True,
            text=True, timeout=1200, env=env, check=False,
        )
        if proc.returncode != 0 or not proc.stdout.strip():
            shard_tick = {
                "error": f"exit {proc.returncode}",
                "stderr_tail": (proc.stderr or "").strip()[-400:],
            }
        else:
            shard_tick = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as exc:
        shard_tick = {"error": f"{type(exc).__name__}: {exc}"}

    # -- device-resident sessions (ISSUE 6): resident-vs-restaged A/B +
    # per-request byte accounting + the isolated staging floor at 2k
    sync_floor_line = sync_floor_metrics(sync_floor_ms, device_2k_ms)

    # -- multi-tenant serving throughput (ISSUE 3): concurrency-16 through
    # the serve scheduler (coalesced batched dispatches) vs the same
    # requests serialized through the solo analyze boundary
    serve_line = serve_throughput_metrics(engine, case)

    # -- serve pool (ISSUE 8): 1-vs-N replica aggregate throughput at
    # concurrency 64 + replica-kill recovery, in a subprocess with an
    # 8-device virtual host so replicas own device groups (same pattern
    # as the sharded-tick dry run below)
    try:
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(env.get("XLA_FLAGS", "")
                       + " --xla_force_host_platform_device_count=8"
                       ).strip(),
        )
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--serve-pool-only"],
            capture_output=True, text=True, timeout=1200, env=env,
            check=False,
        )
        if proc.returncode != 0 or not proc.stdout.strip():
            serve_pool_line = {
                "error": f"exit {proc.returncode}",
                "stderr_tail": (proc.stderr or "").strip()[-400:],
            }
        else:
            serve_pool_line = json.loads(
                proc.stdout.strip().splitlines()[-1]
            )
    except Exception as exc:
        serve_pool_line = {"error": f"{type(exc).__name__}: {exc}"}

    # -- gateway + canary (ISSUE 9): wire-vs-in-process overhead at
    # concurrency 16, honest-429 shed rate at 2x capacity, and the
    # canary regression stream's sample+mint+replay cost
    try:
        gateway_line = gateway_metrics(engine)
    except Exception as exc:
        gateway_line = {"error": f"{type(exc).__name__}: {exc}"}

    # -- serve federation (ISSUE 15): cross-process plane — worker
    # processes over the wire protocol vs the single-process loop, the
    # SIGKILL kill leg (all-terminal + 0 double completions asserted
    # in-run), and lease-expiry detection latency
    try:
        serve_federation_line = serve_federation_metrics()
    except Exception as exc:
        serve_federation_line = {"error": f"{type(exc).__name__}: {exc}"}

    # -- serve autoscale (ISSUE 16): the elastic fleet's 2→8→2 ramp
    # soak — request p50/p99 through both scale transitions, controller
    # decision latency, placement hit rate (exactly-once asserted
    # in-run)
    try:
        serve_autoscale_line = serve_autoscale_metrics()
    except Exception as exc:
        serve_autoscale_line = {"error": f"{type(exc).__name__}: {exc}"}

    # -- observability (ISSUE 11): tracing overhead on/off at
    # concurrency 16, span drop rate under saturation, profile capture
    # cost for a 20-tick window
    try:
        observability_line = observability_metrics(engine, case)
    except Exception as exc:
        observability_line = {"error": f"{type(exc).__name__}: {exc}"}

    # -- causelens attribution (ISSUE 14): per-shape explain-on cost
    # (first vs steady) from the registry's attribution rows; the
    # explain-off serve p50 below feeds bench_guard's tighter 5% gate
    try:
        attribution_line = attribution_metrics(engine)
    except Exception as exc:
        attribution_line = {"error": f"{type(exc).__name__}: {exc}"}
    if isinstance(serve_line, dict):
        attribution_line["explain_off_request_ms_p50"] = serve_line.get(
            "request_ms_p50"
        )

    # -- columnar world state (ISSUE 10): 100k-pod capture, columnar vs
    # dict sweep, coldiff bytes/tick, bit parity asserted in-run
    try:
        columnar_line = columnar_capture_metrics()
    except Exception as exc:
        columnar_line = {"error": f"{type(exc).__name__}: {exc}"}
    columnar_line.update({
        "live_sweep_capture_ms_10k_columnar": round(live_sweep_ms, 3),
        "live_sweep_capture_ms_10k_dict": round(live_sweep_dict_ms, 3),
        "sweep_speedup_10k": round(
            live_sweep_dict_ms / max(live_sweep_ms, 1e-9), 1
        ),
        "parity_ok_10k": bool(columnar_parity_10k),
    })

    # -- planet capture (ISSUE 17): the 1M-pod sustained soak — 10
    # clusters x 100k pods through the LIVE columnar adapter, swept
    # sequentially like a federated ingest fleet; live-vs-dict bit
    # parity asserted in-run
    try:
        planet_line = planet_capture_metrics()
    except Exception as exc:
        planet_line = {"error": f"{type(exc).__name__}: {exc}"}

    # -- accuracy under adversarial cascade modes (VERDICT round-1 item 3):
    # (skippable with --skip-accuracy when only the latency numbers are
    # wanted — this block trains a model and runs ~360 extra analyses)
    # hit@1/hit@3 per mode for the DEFAULT engine (which since round 4
    # loads the packaged trained checkpoint — VERDICT r3 item 2), the
    # hand-set weights ("handset", the pre-checkpoint defaults), a freshly
    # trained fit, and the naive max-anomaly baseline.  The hard modes are
    # built so max-anomaly fails: victims that crash, dropped signals,
    # correlated noise with loud decoys.
    from rca_tpu.engine.propagate import default_params
    from rca_tpu.engine.train import TrainConfig, train

    if skip_accuracy:
        accuracy = None
    else:
        trained_params, _ = train(TrainConfig(
            n_services=256, n_cases=48, iters=150, seed=0,
            modes=("adversarial", "crashing_victims", "correlated_noise",
                   "standard"),
        ))
        trained_engine = GraphEngine(params=trained_params)
        handset_engine = GraphEngine(params=default_params())

        def mode_hits(mode, trials=15, n=500, fault_mix="crash"):
            n_roots = 3 if mode == "overlapping_roots" else 1
            counts = {"engine": [0, 0], "handset": [0, 0],
                      "trained": [0, 0], "naive": [0, 0]}
            for seed in range(trials):
                c = synthetic_cascade_arrays(
                    n, n_roots=n_roots, seed=1000 + seed, mode=mode,
                    fault_mix=fault_mix,
                )
                roots = set(c.roots.tolist())
                for key, scores in (
                    ("engine", engine.analyze_case(c, k=3).score),
                    ("handset", handset_engine.analyze_case(c, k=3).score),
                    ("trained", trained_engine.analyze_case(c, k=3).score),
                    ("naive", c.anomaly),
                ):
                    order = np.argsort(-scores)
                    counts[key][0] += int(order[0]) in roots
                    counts[key][1] += bool(roots & set(order[:3].tolist()))
                del c
            return {
                key: {"hit1": round(v[0] / trials, 3),
                      "hit3": round(v[1] / trials, 3)}
                for key, v in counts.items()
            }

        accuracy = {
            mode: mode_hits(mode)
            for mode in ("standard", "crashing_victims", "missing_signals",
                         "correlated_noise", "overlapping_roots",
                         "adversarial")
        }
        # round-3 fault archetypes: the hardest mode over mixed root-fault
        # kinds (oom/image/config/pending roots alongside crash ones)
        accuracy["adversarial_mixed_faults"] = mode_hits(
            "adversarial", fault_mix="mixed"
        )

    # -- quantized rank-parity gate (ISSUE 13): the landing gate for the
    # int8-message kernel is RANK parity, not bit parity — hit@1/hit@3
    # must MATCH the f32 path across the accuracy modes and the top-k
    # order must hold Kendall-tau >= 0.99.  Runs with the accuracy suite
    # (same --skip-accuracy economics).
    if skip_accuracy:
        quant_parity = None
    else:
        from rca_tpu.engine.quantized import topk_score_tau

        parity_modes = ("standard", "crashing_victims", "missing_signals",
                        "correlated_noise", "overlapping_roots",
                        "adversarial")
        q_trials, q_n = 8, 300
        f32_orders = {}
        for mode in parity_modes:
            for seed in range(q_trials):
                c = synthetic_cascade_arrays(
                    q_n, n_roots=1, seed=2000 + seed, mode=mode,
                )
                res = engine.analyze_case(c, k=5)
                f32_orders[(mode, seed)] = (
                    res.score, set(c.roots.tolist())
                )
        prev_kernel = os.environ.get("RCA_KERNEL")
        os.environ["RCA_KERNEL"] = "quantized"
        try:
            # rows are keyed by the env flag, so the fresh engine's
            # sessions resolve quantized; `engine`'s pinned sessions
            # keep their f32 plans
            q_engine = GraphEngine()
            quant_parity = {"kernel": "quantized", "modes": {}, "ok": True}
            taus_all = []
            for mode in parity_modes:
                h1 = [0, 0]
                h3 = [0, 0]
                taus = []
                for seed in range(q_trials):
                    c = synthetic_cascade_arrays(
                        q_n, n_roots=1, seed=2000 + seed, mode=mode,
                    )
                    q_score = q_engine.analyze_case(c, k=5).score
                    q_order = np.argsort(-q_score)[:3].tolist()
                    f_score, roots = f32_orders[(mode, seed)]
                    f_order = np.argsort(-f_score)[:3].tolist()
                    h1[0] += f_order[0] in roots
                    h1[1] += q_order[0] in roots
                    h3[0] += bool(roots & set(f_order))
                    h3[1] += bool(roots & set(q_order))
                    # tie-aware tau over the top-25 (engine/quantized.py:
                    # sub-int8-step background near-ties carry no rank
                    # signal; separated pairs must keep their order)
                    taus.append(topk_score_tau(f_score, q_score))
                taus_all.extend(taus)
                quant_parity["modes"][mode] = {
                    "hit1_f32": round(h1[0] / q_trials, 3),
                    "hit1_quantized": round(h1[1] / q_trials, 3),
                    "hit3_f32": round(h3[0] / q_trials, 3),
                    "hit3_quantized": round(h3[1] / q_trials, 3),
                    "kendall_tau_min": round(min(taus), 4),
                }
                if h1[0] != h1[1] or h3[0] != h3[1]:
                    quant_parity["ok"] = False
            quant_parity["kendall_tau_min"] = round(min(taus_all), 4)
            quant_parity["kendall_tau_floor"] = 0.99
            if quant_parity["kendall_tau_min"] < 0.99:
                quant_parity["ok"] = False
        finally:
            if prev_kernel is None:
                os.environ.pop("RCA_KERNEL", None)
            else:
                os.environ["RCA_KERNEL"] = prev_kernel

    def r(x, nd=4):
        """Round, passing through None (= honestly unmeasured)."""
        return round(x, nd) if x is not None else None

    # per-shape kernel registry (ISSUE 12/13): resolve the rows this
    # round exercised — WITH their edge tiers, so the edge-layout
    # kernels (segscan/quantized/doubling) show eligibility per row —
    # capture the winner executables' XLA cost analysis for the shapes
    # under the compile cap, and derive BOTH kernel_by_shape and the
    # kernel_registry section from the one table — agreement by
    # construction (the old parallel engaged_kernel bookkeeping is gone)
    from rca_tpu.engine.registry import kernel_table

    _buckets = RCAConfig().shape_buckets
    for _n, _e in ((n_services, result.n_edges),
                   (10_000, len(sk.dep_src)),
                   (50_000, len(big.dep_src))):
        engaged_kernel(bucket_for(_n + 1, _buckets),
                       bucket_for(max(_e, 1), _buckets))
    kernel_rows = kernel_table(ensure_cost=True, cost_max_pad=4096)
    kernel_by_shape = {
        str(row["n_pad"]): row["winner"]
        for row in kernel_rows if row["variant"] == "dense"
    }

    # registry kernel A/B (ISSUE 13 satellite): the full chain under
    # every KERNELS member at the 2k tier — interpret-honest (the
    # section stamps backend + whether Pallas ran interpreted; CPU-host
    # numbers prove mechanics, the real-TPU round stamps speed)
    _tools_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools")
    sys.path.insert(0, _tools_dir)
    try:
        from downscan_bench import registry_kernel_ab
    finally:
        sys.path.remove(_tools_dir)
    kernel_ab = registry_kernel_ab(tiers=(2_000,))

    target_ms = 150.0
    line = {
        "metric": "rca_graph_inference_latency_2k_service",
        "value": round(result.latency_ms, 3),
        "unit": "ms",
        "vs_baseline": round(target_ms / max(result.latency_ms, 1e-6), 2),
        "top1_hit_2k_3root": bool(top1_hit),
        "all_roots_in_topk_2k": bool(all_roots_topk),
        "hit_at_1_500svc": hits / trials,
        "n_services": n_services,
        "n_edges": result.n_edges,
        "sync_floor_ms": round(sync_floor_ms, 3),
        # the headline minus the per-sync transport round trip (round 5,
        # VERDICT r4 item 5): the <150 ms gate judged on WORK, not the
        # tunnel RTT of the day — the raw floor varied 90-135 ms across
        # rounds while device compute held still.  Raw `value` stays the
        # honest end-to-end number a deployment pays.
        "e2e_floor_subtracted_ms": round(
            max(result.latency_ms - sync_floor_ms, 0.0), 3
        ),
        "vs_baseline_floor_subtracted": round(
            target_ms / max(result.latency_ms - sync_floor_ms, 1e-6), 2
        ),
        "device_compute_ms_2k": r(device_2k_ms),
        # resident-vs-restaged A/B, staging floor, bytes/request (ISSUE 6)
        "sync_floor": sync_floor_line,
        "latency_50k_amortized_ms": r(big_ms),
        "top1_hit_50k": bool(big_top1),
        "batch16_2k_dispatch_ms": round(batch_ms, 3),
        "batch64_marginal_per_hypothesis_ms_2k": r(batch_marginal_ms),
        "batch64_marginal_jitter_ms": r(batch_marginal_jitter_ms),
        "serve_throughput_2k": serve_line,
        # multi-replica serving plane (ISSUE 8): aggregate inv/s 1-vs-N
        # replicas at concurrency 64, replica-kill recovery, occupancy
        "serve_pool": serve_pool_line,
        # wire front door + canary (ISSUE 9): loopback overhead p50/p99,
        # 429 shed rate at 2x capacity, canary replay throughput
        "gateway": gateway_line,
        # cross-process federation (ISSUE 15): wire-hop overhead vs the
        # single-process loop, kill-leg recovery_ms, lease detect lag
        "serve_federation": serve_federation_line,
        # elastic fleet (ISSUE 16): 2→8→2 ramp latency through the
        # transitions, scale-decision latency, placement hit rate
        "serve_autoscale": serve_autoscale_line,
        # tracing (ISSUE 11): overhead on/off, drop rate, profile cost
        "observability": observability_line,
        "tick_ms_10k": round(tick_ms_10k, 3),
        "tick_ms_10k_pipelined": round(tick_ms_10k_pipelined, 3),
        "tick_pipeline_speedup_10k": round(
            tick_ms_10k / max(tick_ms_10k_pipelined, 1e-3), 2
        ),
        "tick_pipeline_parity_ok_10k": bool(pipeline_parity_ok),
        "tick_phases_10k": phase_medians(serial_phases),
        "tick_phases_10k_pipelined": phase_medians(pipe_phases),
        "tick_upload_rows_10k": tick_upload_rows,
        "live_quiet_capture_ms_10k": round(live_quiet_ms, 3),
        # columnar since round 10 (ISSUE 10) — the dict baseline and the
        # in-run parity gate live in the columnar_capture section
        "live_sweep_capture_ms_10k": round(live_sweep_ms, 3),
        # columnar world state (ISSUE 10): 100k-pod capture + coldiff
        # bytes/tick + columnar-vs-dict sweep ratio and parity bits
        "columnar_capture": columnar_line,
        # planet capture (ISSUE 17): 1M pods aggregate across 10
        # simulated clusters through the live columnar adapter —
        # sweep/busy/quiet tick percentiles + coldiff bytes per cluster
        "planet_capture": planet_line,
        "live_recovery_capture_ms_10k": round(live_recovery_ms, 3),
        "live_recovery_graceful": live_recovered,
        "sharded_stream_tick_50k_dryrun": shard_tick,
        "sharded_stream_tick_50k_pipelined": (
            r(shard_tick.get("tick_ms_pipelined"), 3)
            if isinstance(shard_tick, dict) else None
        ),
        "live_watch_capture_speedup": round(
            live_sweep_ms / max(live_quiet_ms, 1e-3), 1
        ),
        "segscan_engaged_50k": big_down_seg is not None,
        "pallas_supported": bool(pallas_ok),
        "pallas_engaged": bool(pallas_enabled()),  # reflects RCA_PALLAS env
        # (the retired process-level noisyor_path stamp is gone — ISSUE
        # 14 satellite; kernel_by_shape below says strictly more)
        # per-shape engaged kernel + the full registry rows (ISSUE 12):
        # both derive from engine/registry.py's table, so a pallas
        # regression names a shape AND the row shows why (timings,
        # eligibility, FLOPs/bytes/peak-memory from XLA cost analysis)
        "kernel_by_shape": kernel_by_shape,
        "kernel_registry": kernel_rows,
        # full-chain A/B of every registry kernel at the 2k tier
        # (ISSUE 13; tools/downscan_bench.py --ab prints bigger tiers)
        "kernel_ab": kernel_ab,
        "xla_noisyor_50k_ms": r(xla_nor_ms),
        "pallas_noisyor_50k_ms": r(pallas_nor_ms),
        # causelens (ISSUE 14): per-shape attribution cost + the
        # explain-off serve p50 the bench_guard 5% gate compares
        "attribution": attribution_line,
        # flight recorder: record overhead, log size, replay throughput
        "replay": replay_metrics(),
        # analyzer wall time: lint gates every PR, so it is benched too
        "graftlint": lint_metrics(),
        "backend": "jax",
        "engine": result.engine,  # which engine the analyze boundary ran
    }
    if accuracy is not None:
        line["accuracy_by_mode"] = accuracy
    if quant_parity is not None:
        # the quantized kernel's landing gate (ISSUE 13): rank parity
        # vs f32 — hit@1/hit@3 equal, Kendall-tau >= 0.99 on top-k
        line["quantized_rank_parity"] = quant_parity
    if with_chaos:
        line["chaos_soak_50svc"] = chaos_metrics(
            seed=int(os.environ.get("RCA_CHAOS_SEED", "7"))
        )
    print(json.dumps(line), file=real_stdout, flush=True)
    if guard:
        # bench post-step (ISSUE 12 satellite): compare THIS line against
        # the last committed BENCH_r*.json and fail on >15% regression in
        # the named headline metrics (tools/bench_guard.py)
        tools_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools")
        sys.path.insert(0, tools_dir)
        try:
            from bench_guard import check_line
        finally:
            sys.path.remove(tools_dir)
        report = check_line(
            line, os.path.dirname(os.path.abspath(__file__))
        )
        print(json.dumps({"bench_guard": report}), file=sys.stderr,
              flush=True)
        return 0 if report["ok"] else 1
    return 0


if __name__ == "__main__":
    if "--serve-pool-only" in sys.argv[1:]:
        # subprocess entry for the serve_pool section (run by main
        # inside an 8-virtual-device host): the JSON dict is the SOLE
        # stdout line, chatter goes to stderr like the main bench
        _real = sys.stdout
        sys.stdout = sys.stderr
        try:
            _pool_line = serve_pool_metrics()
        finally:
            sys.stdout = _real
        print(json.dumps(_pool_line), flush=True)
        sys.exit(0)
    sys.exit(main(
        skip_accuracy="--skip-accuracy" in sys.argv[1:],
        with_chaos="--chaos" in sys.argv[1:],
        guard="--guard" in sys.argv[1:],
    ))
