"""Headline benchmark: RCA graph-inference latency on a 2k-service cascade.

Measures the north-star metric (BASELINE.json): median device latency of the
jit'd explain-away propagation + top-k ranking over a 2,000-service synthetic
fault cascade (3 concurrent roots), and whether the true roots are ranked
top-1/top-k.  Baseline target: < 150 ms on TPU v5e-1 with top-1 hit.
``vs_baseline`` = 150 / measured_ms (higher is better; >1 beats target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import json
import sys


def main() -> int:
    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import GraphEngine

    n_services = 2000
    n_roots = 3
    case = synthetic_cascade_arrays(n_services, n_roots=n_roots, seed=0)
    engine = GraphEngine()
    result = engine.analyze_case(case, k=5, timed=True)

    truth = {case.names[r] for r in case.roots.tolist()}
    top1_hit = result.ranked[0]["component"] in truth
    topk = set(result.top_components(n_roots))
    all_roots_topk = truth <= topk

    # hit@1 across seeds for a robust accuracy figure (single-root cases)
    hits = 0
    trials = 20
    for seed in range(trials):
        c = synthetic_cascade_arrays(500, n_roots=1, seed=seed)
        r = engine.analyze_case(c, k=1)
        hits += r.ranked[0]["component"] == c.names[c.roots[0]]

    target_ms = 150.0
    line = {
        "metric": "rca_graph_inference_latency_2k_service",
        "value": round(result.latency_ms, 3),
        "unit": "ms",
        "vs_baseline": round(target_ms / max(result.latency_ms, 1e-6), 2),
        "top1_hit_2k_3root": bool(top1_hit),
        "all_roots_in_topk_2k": bool(all_roots_topk),
        "hit_at_1_500svc": hits / trials,
        "n_services": n_services,
        "n_edges": result.n_edges,
        "backend": "jax",
    }
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
